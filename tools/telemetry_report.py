#!/usr/bin/env python
"""Telemetry report — summarize observability artifacts into tables.

Reads any mix of:
- a ``scalars.jsonl`` written by ``optim.summary.Summary`` (tag/value/
  step/wall records) → per-tag count/min/mean/last plus step-interval
  percentiles from the wall clocks;
- a Chrome-trace JSON exported by ``observability.export_chrome_trace``
  → per-span-name duration percentiles (p50/p90/p99);
- (library use) the live metric registry → the same summary ``bench.py``
  appends to its output record.

CLI:
    python tools/telemetry_report.py run/log/train/scalars.jsonl
    python tools/telemetry_report.py trace.json
    python tools/telemetry_report.py --json trace.json   # machine output
    python tools/telemetry_report.py --trace <id> trace.json  # one
        request's spans only (distributed-trace filter, ISSUE 3)
    python tools/telemetry_report.py --fleet snapA.json snapB.json
        # percentile tables from /metrics/snapshot docs — sketch
        # series resolve to EXACT sketch quantiles (ISSUE 12), not
        # bucket interpolation
    python tools/telemetry_report.py --explain <request_id> [flight.json]
        # one request's flight-recorder decision timeline + verdict
        # (ISSUE 16): from a saved /debug/explain or /debug/flight
        # JSON, or — with no path — the in-process flight ring
    python tools/telemetry_report.py --watch host:port \
            --series bigdl_llm_queue_depth[,more...] \
            [--fn last] [--window 60] [--interval 2] [--count N]
        # live terminal sparklines over GET /metrics/query (ISSUE 18):
        # one row per series, redrawn every --interval seconds against
        # a worker/router/supervisor with the time-series plane on

The registry summary (library use) carries the live utilization gauges
(``bigdl_device_mfu`` / ``bigdl_device_hbm_bw_gbps`` /
``bigdl_device_bw_util``) whenever the flight recorder has sampled
dispatches — they are ordinary gauges in the same registry.

Quantile sources (ISSUE 12): where a metric is backed by a quantile
sketch, every percentile this tool prints is the sketch's own value
(bounded relative error, mergeable across workers); bucket-boundary
interpolation remains only for plain fixed-bucket histograms.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Dict, List, Optional

# runnable both as `python tools/telemetry_report.py` and as an import:
# the script dir is on sys.path then, the package root is not
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(int(math.ceil(q * len(sorted_vals))) - 1,
              len(sorted_vals) - 1)
    return sorted_vals[max(idx, 0)]


def _dist(vals: List[float]) -> Dict[str, float]:
    s = sorted(vals)
    return {"count": len(s),
            "mean": sum(s) / len(s),
            "p50": _pct(s, 0.50),
            "p90": _pct(s, 0.90),
            "p95": _pct(s, 0.95),
            "p99": _pct(s, 0.99),
            "max": s[-1]}


def summarize_scalars(path: str) -> dict:
    """Per-tag stats from a Summary JSONL scalar log; ``step_seconds``
    holds the wall-clock interval distribution between consecutive
    records of the most frequent tag (≈ step time for a Loss stream)."""
    tags: Dict[str, List[dict]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            tags.setdefault(rec["tag"], []).append(rec)
    out: Dict[str, dict] = {"tags": {}}
    for tag, recs in tags.items():
        vals = [r["value"] for r in recs]
        out["tags"][tag] = {
            "count": len(recs), "min": min(vals),
            "mean": sum(vals) / len(vals), "last": vals[-1]}
    if tags:
        main_tag = max(tags, key=lambda t: len(tags[t]))
        walls = [r["wall"] for r in tags[main_tag]]
        deltas = [b - a for a, b in zip(walls, walls[1:]) if b >= a]
        if deltas:
            out["step_seconds"] = dict(_dist(deltas), tag=main_tag)
    return out


def summarize_trace(path_or_doc, trace_id: Optional[str] = None) -> dict:
    """Per-span-name duration distributions (p50/p95/p99 among them)
    from Chrome-trace JSON. ``trace_id`` restricts to one request's
    spans (the ISSUE 3 distributed-trace tag), making latency exemplars
    scriptable: feed an id from ``GET /debug/traces`` straight in."""
    if isinstance(path_or_doc, dict):
        doc = path_or_doc
    else:
        with open(path_or_doc) as f:
            doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    names: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if trace_id is not None and \
                ev.get("args", {}).get("trace") != trace_id:
            continue
        names.setdefault(ev["name"], []).append(ev["dur"] / 1e6)
    out = {"spans": {name: _dist(d)
                     for name, d in sorted(names.items())}}
    if trace_id is not None:
        out["trace_id"] = trace_id
    return out


def summarize_registry(registry=None) -> dict:
    """Compact snapshot of the live metric registry (every counter/gauge
    value, histogram count/mean/p50/p99) — the block ``bench.py`` embeds
    in its output record. Sketch-backed series report EXACT sketch
    quantiles (bounded relative error, ISSUE 12) instead of the
    histogram bucket interpolation."""
    from bigdl_tpu import observability as obs
    from bigdl_tpu.observability.metrics import (_HistogramChild,
                                                 _SketchChild)
    registry = registry or obs.REGISTRY
    out: Dict[str, object] = {}
    for m in registry.collect():
        series = {}
        for key, child in sorted(m.children()):
            label = ",".join(f"{n}={v}" for n, v in zip(m.labelnames, key))
            if isinstance(child, _HistogramChild):
                _, total, count = child.snapshot()
                series[label or "_"] = {
                    "count": count,
                    "mean": (total / count) if count else None,
                    "p50": child.percentile(0.5),
                    "p99": child.percentile(0.99)}
            elif isinstance(child, _SketchChild):
                count = child.count
                series[label or "_"] = {
                    "count": count,
                    "mean": (child.sum / count) if count else None,
                    "p50": child.quantile(0.5),
                    "p95": child.quantile(0.95),
                    "p99": child.quantile(0.99),
                    "sketch": True}
            else:
                series[label or "_"] = child.value
        if series:
            out[m.name] = series if m.labelnames else series["_"]
    return out


def summarize_fleet(paths: List[str]) -> dict:
    """Percentile tables from saved ``/metrics/snapshot`` documents
    (ISSUE 12): per-instance and merged sketch quantiles, exact to the
    sketch's relative-error bound. Loading and row construction are
    fleet_report's — one column mapping, not two."""
    from tools.fleet_report import load_snapshots, sketch_dicts
    return {"kind": "fleet", "paths": list(paths),
            "sketches": sketch_dicts(load_snapshots(paths))}


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _print_table(title: str, header: List[str], rows: List[List]):
    rows = [[_fmt(c) for c in r] for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(header)]
    print(f"\n== {title} ==")
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))


def report(path: str, as_json: bool = False,
           trace_id: Optional[str] = None) -> dict:
    if path.endswith(".jsonl"):
        summary = {"kind": "scalars", "path": path,
                   **summarize_scalars(path)}
    else:
        summary = {"kind": "trace", "path": path,
                   **summarize_trace(path, trace_id=trace_id)}
    if as_json:
        print(json.dumps(summary))
        return summary
    if summary["kind"] == "scalars":
        _print_table(
            f"scalars: {path}",
            ["tag", "count", "min", "mean", "last"],
            [[t, d["count"], d["min"], d["mean"], d["last"]]
             for t, d in sorted(summary["tags"].items())])
        st = summary.get("step_seconds")
        if st:
            _print_table(
                f"step time (wall deltas of '{st['tag']}')",
                ["count", "mean_s", "p50_s", "p90_s", "p95_s", "p99_s",
                 "max_s"],
                [[st["count"], st["mean"], st["p50"], st["p90"],
                  st["p95"], st["p99"], st["max"]]])
    else:
        title = f"trace spans: {path}"
        if trace_id is not None:
            title += f" (trace {trace_id})"
        _print_table(
            title,
            ["span", "count", "mean_s", "p50_s", "p90_s", "p95_s",
             "p99_s", "max_s"],
            [[name, d["count"], d["mean"], d["p50"], d["p90"], d["p95"],
              d["p99"], d["max"]]
             for name, d in summary["spans"].items()])
    return summary


def summarize_explain(request_id: str,
                      path: Optional[str] = None) -> dict:
    """One request's flight timeline (ISSUE 16): from a saved
    ``/debug/explain`` / ``/debug/flight`` JSON document, or from the
    in-process flight ring when ``path`` is None."""
    if path is not None:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("request") == request_id and "verdict" in doc:
            return doc   # already an explain doc for this request
        # a /debug/flight ring dump: assemble the timeline ourselves
        from bigdl_tpu.observability.flight import _verdict
        events = [e for e in doc.get("events", [])
                  if e.get("request") == request_id]
        traces = {e["trace"] for e in events if e.get("trace")}
        events += [e for e in doc.get("events", [])
                   if e.get("request") != request_id
                   and e.get("trace") in traces]
        events.sort(key=lambda e: e.get("seq", 0))
        return {"request": request_id, "traces": sorted(traces),
                "verdict": _verdict(events), "events": events}
    from bigdl_tpu.observability import flight
    return flight.explain(request_id)


# ---------------------------------------------------------------------------
# live watch over /metrics/query (ISSUE 18)
# ---------------------------------------------------------------------------

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[Optional[float]], width: int = 40) -> str:
    """Terminal sparkline of the last ``width`` values; ``None`` (no
    data in the window yet) renders as a gap."""
    vals = list(values)[-width:]
    known = [v for v in vals if v is not None]
    if not known:
        return " " * len(vals)
    lo, hi = min(known), max(known)
    span = (hi - lo) or 1.0
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        else:
            idx = int((v - lo) / span * (len(SPARK_CHARS) - 1))
            out.append(SPARK_CHARS[idx])
    return "".join(out)


def parse_target(target: str):
    """``host:port`` → (host, int(port))."""
    host, _, port = target.rpartition(":")
    return (host or "127.0.0.1", int(port))


def query_value(addr, series: str, fn: str = "last",
                window: float = 60.0,
                timeout: float = 2.0) -> Optional[float]:
    """One ``GET /metrics/query`` roundtrip → the windowed value
    (None = empty window). Raises on HTTP errors — a 404 means the
    target's time-series plane is off, and the watcher should say so
    instead of drawing blanks."""
    import http.client
    from urllib.parse import quote
    path = (f"/metrics/query?series={quote(series, safe='')}"
            f"&fn={quote(fn)}&window={window}")
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = json.loads(resp.read().decode() or "{}")
        if resp.status != 200:
            raise RuntimeError(
                f"{addr[0]}:{addr[1]}{path} answered {resp.status}: "
                f"{body.get('error', '?')}")
        return body.get("value")
    finally:
        conn.close()


def run_watch(target: str, series: List[str], fn: str = "last",
              window: float = 60.0, interval: float = 2.0,
              count: Optional[int] = None, width: int = 40,
              out=print) -> int:
    """Poll ``/metrics/query`` and redraw one sparkline row per series
    until interrupted (or for ``count`` rounds — the tests' hook)."""
    addr = parse_target(target)
    history: Dict[str, List[Optional[float]]] = {s: [] for s in series}
    rounds = 0
    import time as _time
    while count is None or rounds < count:
        if rounds:
            _time.sleep(interval)
        rounds += 1
        for s in series:
            try:
                val = query_value(addr, s, fn=fn, window=window)
            except Exception as e:   # noqa: BLE001 — show, keep going
                out(f"{s}: {e}")
                continue
            h = history[s]
            h.append(val)
            del h[:-width]
            out(f"{s}  {fn}/{window:g}s  "
                f"last={_fmt(val)}  {sparkline(h, width)}")
    return 0


def main(argv: List[str]) -> int:
    as_json = "--json" in argv
    if "--watch" in argv:
        def _opt(flag, default=None):
            if flag in argv:
                i = argv.index(flag)
                if i + 1 < len(argv):
                    return argv[i + 1]
            return default
        target = _opt("--watch")
        series = [s for s in (_opt("--series") or "").split(",") if s]
        if not target or not series:
            print("--watch host:port needs --series name[,name...]",
                  file=sys.stderr)
            return 2
        count = _opt("--count")
        return run_watch(
            target, series, fn=_opt("--fn", "last"),
            window=float(_opt("--window", "60")),
            interval=float(_opt("--interval", "2")),
            count=int(count) if count is not None else None)
    trace_id = None
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 >= len(argv):
            print("--trace needs a trace id", file=sys.stderr)
            return 2
        trace_id = argv[i + 1]
    explain_id = None
    if "--explain" in argv:
        i = argv.index("--explain")
        if i + 1 >= len(argv):
            print("--explain needs a request id", file=sys.stderr)
            return 2
        explain_id = argv[i + 1]
    paths = [a for i, a in enumerate(argv)
             if not a.startswith("--")
             and (i == 0 or argv[i - 1] not in ("--trace", "--explain"))]
    if explain_id is not None:
        path = paths[0] if paths else None
        if path is not None and not os.path.exists(path):
            print(f"no such file: {path}", file=sys.stderr)
            return 1
        summary = summarize_explain(explain_id, path)
        if as_json:
            print(json.dumps(summary))
        else:
            from tools.explain_report import render
            render(summary)
        return 0
    if "--fleet" in argv:
        if not paths:
            print("--fleet needs /metrics/snapshot JSON files",
                  file=sys.stderr)
            return 2
        for p in paths:
            if not os.path.exists(p):
                print(f"no such file: {p}", file=sys.stderr)
                return 1
        summary = summarize_fleet(paths)
        if as_json:
            print(json.dumps(summary))
        else:
            _print_table(
                "fleet sketch percentiles (ms, exact sketch quantiles)",
                ["instance", "series", "n", "p50", "p90", "p95", "p99",
                 "max"],
                [[s["instance"], s["series"], s["count"], s["p50_ms"],
                  s["p90_ms"], s["p95_ms"], s["p99_ms"], s["max_ms"]]
                 for s in summary["sketches"]])
        return 0
    if not paths:
        print(__doc__)
        return 2
    for p in paths:
        if not os.path.exists(p):
            print(f"no such file: {p}", file=sys.stderr)
            return 1
        report(p, as_json=as_json, trace_id=trace_id)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

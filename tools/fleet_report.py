#!/usr/bin/env python
"""Fleet telemetry report (ISSUE 12 satellite): the per-backend and
merged view of a federated metric plane.

Three entry points:

- **Live fleet**: point it at a federation-enabled router (or elastic
  supervisor) — it fetches ``/fleet/status`` plus each member's
  ``/metrics/snapshot`` and prints per-instance and merged tables::

      python tools/fleet_report.py --url 127.0.0.1:8000

- **Offline snapshots**: merge saved ``/metrics/snapshot`` JSON
  documents (one file per member)::

      python tools/fleet_report.py snapA.json snapB.json [--json]

- **Fleet timeline** (ISSUE 18): per-member + merged series over time
  from a time-series-enabled router/supervisor's ``/fleet/timeline``::

      python tools/fleet_report.py --timeline 127.0.0.1:8000 \\
          --series bigdl_llm_decode_tokens_total [--window 300]

- **Library** (``run_fleet_micro``): spin up two tiny decode workers
  behind a failover router with federation + SLO accounting on, route
  a small request mix, and return the merged sketch percentiles
  (``ttft_p50/p95/p99_ms``, ``itl_p99_ms``) plus a counter-additivity
  check — the ``fleet`` block ``bench.py`` embeds in its telemetry so
  ``tools/bench_regress.py`` can diff fleet tail latency across
  rounds.

The percentile columns come from the merged quantile sketches — exact
to the sketch's stated relative-error bound, not bucket-interpolated.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: sketch series the percentile tables highlight, in render order
_LATENCY_SKETCHES = (
    "bigdl_router_ttft_seconds", "bigdl_router_itl_seconds",
    "bigdl_llm_ttft_seconds", "bigdl_llm_itl_seconds")


def _http_get(addr: Tuple[str, int], path: str, timeout: float = 10.0):
    import http.client
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw
    finally:
        conn.close()


def sketch_rows(snapshots: Dict[str, dict]) -> List[List]:
    """Per-instance AND merged percentile rows for every sketch series
    found in ``snapshots`` ({instance: snapshot doc})."""
    from bigdl_tpu.observability.federation import merge_snapshots
    from bigdl_tpu.observability.sketch import QuantileSketch

    rows: List[List] = []

    def add_rows(instance: str, doc: dict):
        for mdoc in doc.get("metrics", []):
            if mdoc.get("kind") != "summary":
                continue
            for s in mdoc.get("series", []):
                if "sketch" not in s:
                    continue
                sk = QuantileSketch.from_snapshot(s["sketch"])
                if sk.count == 0:
                    continue
                label = ",".join(str(v) for v in s.get("labels", []))
                rows.append([
                    instance, mdoc["name"] + (f"{{{label}}}" if label
                                              else ""),
                    sk.count,
                    _ms(sk.quantile(0.5)), _ms(sk.quantile(0.9)),
                    _ms(sk.quantile(0.95)), _ms(sk.quantile(0.99)),
                    _ms(sk.max)])

    for instance in sorted(snapshots):
        add_rows(instance, snapshots[instance])
    add_rows("MERGED", merge_snapshots(snapshots))
    # stable, sketch-catalog-first ordering
    prio = {n: i for i, n in enumerate(_LATENCY_SKETCHES)}
    rows.sort(key=lambda r: (r[0] != "MERGED",
                             prio.get(r[1].split("{")[0], 99), r[0]))
    return rows


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


def counter_table(snapshots: Dict[str, dict],
                  names: Optional[List[str]] = None) -> List[List]:
    """Per-instance + summed rows for counters (the merge-correctness
    view: MERGED must equal the per-instance sum)."""
    from bigdl_tpu.observability.federation import merge_snapshots
    per: Dict[str, Dict[str, float]] = {}
    for instance, doc in snapshots.items():
        for mdoc in doc.get("metrics", []):
            if mdoc.get("kind") != "counter":
                continue
            if names and mdoc["name"] not in names:
                continue
            total = sum(float(s.get("value", 0.0))
                        for s in mdoc.get("series", []))
            per.setdefault(mdoc["name"], {})[instance] = total
    merged = merge_snapshots(snapshots)
    fed: Dict[str, float] = {}
    for mdoc in merged.get("metrics", []):
        if mdoc.get("kind") == "counter":
            fed[mdoc["name"]] = sum(float(s.get("value", 0.0))
                                    for s in mdoc.get("series", []))
    rows = []
    for name in sorted(per):
        inst = per[name]
        rows.append([name, round(sum(inst.values()), 6),
                     round(fed.get(name, 0.0), 6),
                     " ".join(f"{i}={v:g}"
                              for i, v in sorted(inst.items()))])
    return rows


def _print_table(title: str, header: List[str], rows: List[List]):
    def fmt(v):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.6g}"
        return str(v)
    rows = [[fmt(c) for c in r] for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(header)]
    print(f"\n== {title} ==")
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))


def sketch_dicts(snapshots: Dict[str, dict]) -> List[dict]:
    """The sketch percentile rows as dicts — shared by this report and
    ``telemetry_report --fleet`` so the column mapping lives once."""
    return [{"instance": r[0], "series": r[1], "count": r[2],
             "p50_ms": r[3], "p90_ms": r[4], "p95_ms": r[5],
             "p99_ms": r[6], "max_ms": r[7]}
            for r in sketch_rows(snapshots)]


def load_snapshots(paths: List[str]) -> Dict[str, dict]:
    """Saved ``/metrics/snapshot`` docs keyed by their embedded
    instance name (file basename when absent)."""
    snapshots: Dict[str, dict] = {}
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        snapshots[doc.get("instance") or os.path.basename(p)] = doc
    return snapshots


def report(snapshots: Dict[str, dict], as_json: bool = False,
           status: Optional[dict] = None) -> dict:
    out = {
        "instances": sorted(snapshots),
        "sketches": sketch_dicts(snapshots),
        "counters": [
            {"name": r[0], "sum": r[1], "federated": r[2], "per": r[3]}
            for r in counter_table(snapshots)],
    }
    if status is not None:
        out["fleet_status"] = status
    if as_json:
        print(json.dumps(out))
        return out
    if status is not None:
        _print_table(
            "fleet members", ["instance", "stale", "scrapes",
                              "failures", "age_s"],
            [[n, m["stale"], m["scrapes"], m["failures"],
              m["last_scrape_age_s"]]
             for n, m in sorted(status.get("members", {}).items())])
    _print_table(
        "sketch percentiles (ms)",
        ["instance", "series", "n", "p50", "p90", "p95", "p99", "max"],
        sketch_rows(snapshots))
    _print_table(
        "counters (federated must equal the per-instance sum)",
        ["counter", "sum", "federated", "per-instance"],
        counter_table(snapshots))
    return out


# ---------------------------------------------------------------------------
# bench.py telemetry block
# ---------------------------------------------------------------------------

def run_fleet_micro(n_requests: int = 6, new_tokens: int = 4) -> Dict:
    """Two tiny decode workers behind a federation+SLO failover router;
    returns merged sketch percentiles and the counter-additivity
    verdict (the ``fleet`` telemetry block)."""
    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
    from bigdl_tpu.observability.federation import merge_snapshots
    from bigdl_tpu.observability.sketch import QuantileSketch

    if not obs.enabled():
        return {"skipped": "observability disabled"}
    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 250, 8 + 2 * (j % 3)).astype(np.int32)
               for j in range(n_requests)]
    # sketch counts are reported as DELTAS: the process registry is
    # shared with whatever ran before this block (e.g. the chaos storm)
    base_ttft = obs.REGISTRY.sample_value(
        "bigdl_router_ttft_seconds") or 0
    base_itl = obs.REGISTRY.sample_value(
        "bigdl_router_itl_seconds") or 0
    s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   slo=True).start()
    s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   slo=True).start()
    w1 = LLMWorker(s1, role="decode", federation=True).start()
    w2 = LLMWorker(s2, role="decode", federation=True).start()
    router = LLMRouter([], [w1.address, w2.address], failover=True,
                       slo=True, federation=True,
                       start_prober=False).start()
    try:
        import http.client

        def post(addr, path, body):
            conn = http.client.HTTPConnection(*addr, timeout=120)
            try:
                conn.request("POST", path, json.dumps(body),
                             {"Content-Type": "application/json"})
                r = conn.getresponse()
                return r.status, json.loads(r.read().decode())
            finally:
                conn.close()

        # warm both engines on every prompt length so compile time
        # doesn't pollute the tail percentiles
        lengths = sorted({len(p) for p in prompts})
        for srv in (s1, s2):
            for n in lengths:
                srv.submit(prompts[0][:1].repeat(n),
                           max_new_tokens=1).get(timeout=600)
        ok = 0
        for p in prompts:
            st, _ = post(router.address, "/worker_generate",
                         {"prompt_ids": [int(t) for t in p],
                          "max_new_tokens": new_tokens})
            ok += (st == 200)
        router._collector.collect_now()
        snaps = {name: snap
                 for name, snap in router._collector.snapshots().items()
                 if name != "router"}
        merged = merge_snapshots(router._collector.snapshots())
        out: Dict = {"requests": n_requests, "succeeded": ok,
                     "members": sorted(snaps)}
        # merged percentiles from the fleet view. Note: colocated test
        # members share one process registry, so merged COUNTS are
        # N_members × the true count — quantiles are unaffected
        # (merging copies of a sketch preserves its distribution); the
        # honest per-request counts below come from the local registry
        for mdoc in merged.get("metrics", []):
            if mdoc["name"] == "bigdl_router_ttft_seconds":
                for s in mdoc["series"]:
                    sk = QuantileSketch.from_snapshot(s["sketch"])
                    out["ttft_p50_ms"] = _ms(sk.quantile(0.5))
                    out["ttft_p95_ms"] = _ms(sk.quantile(0.95))
                    out["ttft_p99_ms"] = _ms(sk.quantile(0.99))
            if mdoc["name"] == "bigdl_router_itl_seconds":
                for s in mdoc["series"]:
                    sk = QuantileSketch.from_snapshot(s["sketch"])
                    out["itl_p99_ms"] = _ms(sk.quantile(0.99))
        out["ttft_count"] = (obs.REGISTRY.sample_value(
            "bigdl_router_ttft_seconds") or 0) - base_ttft
        out["itl_count"] = (obs.REGISTRY.sample_value(
            "bigdl_router_itl_seconds") or 0) - base_itl
        # counter additivity: the federated value must equal the sum
        # of what the members reported (the acceptance-criterion check,
        # run on every bench round)
        name = "bigdl_llm_decode_tokens_total"
        member_sum = 0.0
        for snap in snaps.values():
            for mdoc in snap.get("metrics", []):
                if mdoc["name"] == name:
                    member_sum += sum(float(s.get("value", 0.0))
                                      for s in mdoc.get("series", []))
        fed_members = merge_snapshots(snaps)
        fed = 0.0
        for mdoc in fed_members.get("metrics", []):
            if mdoc["name"] == name:
                fed = sum(float(s.get("value", 0.0))
                          for s in mdoc.get("series", []))
        out["counter_additive"] = abs(fed - member_sum) < 1e-9
        out["slo"] = (router._slo.status()
                      if router._slo is not None else None)
        return out
    finally:
        router.stop()
        w1.stop()
        w2.stop()
        s1.stop()
        s2.stop()


def fetch_timeline(addr: Tuple[str, int], series: str,
                   window: Optional[float] = None) -> dict:
    """One ``GET /fleet/timeline`` roundtrip → the timeline document.
    Raises with the body's error on non-200 (404 names the gate)."""
    from urllib.parse import quote
    path = f"/fleet/timeline?series={quote(series, safe='')}"
    if window is not None:
        path += f"&window={window}"
    st, raw = _http_get(addr, path)
    body = json.loads(raw.decode() or "{}")
    if st != 200:
        raise RuntimeError(
            f"{addr[0]}:{addr[1]}{path} answered {st}: "
            f"{body.get('error', '?')} — is "
            "bigdl.observability.timeseries.enabled on?")
    return body


def timeline_report(doc: dict, as_json: bool = False) -> dict:
    """Render one ``/fleet/timeline`` document: a sparkline row per
    member plus the merged series."""
    if as_json:
        print(json.dumps(doc))
        return doc
    from tools.telemetry_report import sparkline
    rows = []
    for inst, pts in sorted(doc.get("instances", {}).items()):
        vals = [v for _, v in pts]
        rows.append([inst, len(pts),
                     vals[0] if vals else None,
                     vals[-1] if vals else None,
                     sparkline(vals)])
    merged = doc.get("merged", [])
    mvals = [v for _, v in merged]
    rows.append(["MERGED", len(merged),
                 mvals[0] if mvals else None,
                 mvals[-1] if mvals else None, sparkline(mvals)])
    _print_table(
        f"fleet timeline: {doc.get('series')} "
        f"({doc.get('samples', 0)} samples)",
        ["instance", "points", "first", "last", "trend"], rows)
    return doc


def main(argv: List[str]) -> int:
    as_json = "--json" in argv
    if "--micro" in argv:
        print(json.dumps(run_fleet_micro()))
        return 0
    if "--timeline" in argv:
        def _opt(flag, default=None):
            if flag in argv:
                i = argv.index(flag)
                if i + 1 < len(argv):
                    return argv[i + 1]
            return default
        target = _opt("--timeline")
        series = _opt("--series")
        if not target or not series:
            print("--timeline host:port needs --series name",
                  file=sys.stderr)
            return 2
        host, port = target.replace("http://", "").split(":")
        window = _opt("--window")
        try:
            doc = fetch_timeline(
                (host, int(port)), series,
                window=float(window) if window else None)
        except RuntimeError as e:
            print(str(e), file=sys.stderr)
            return 1
        timeline_report(doc, as_json=as_json)
        return 0
    if "--url" in argv:
        i = argv.index("--url")
        if i + 1 >= len(argv):
            print("--url needs host:port", file=sys.stderr)
            return 2
        host, port = argv[i + 1].replace("http://", "").split(":")
        addr = (host, int(port))
        st, raw = _http_get(addr, "/fleet/status")
        if st != 200:
            print(f"{addr[0]}:{addr[1]}/fleet/status answered {st} — "
                  "is bigdl.observability.federation on?",
                  file=sys.stderr)
            return 1
        status = json.loads(raw.decode())
        snapshots: Dict[str, dict] = {}
        for name, member in status.get("members", {}).items():
            # scrape target: the advertised address (elastic members
            # are named "pidN"); an addressless legacy status falls
            # back to parsing the name
            target = member.get("address") or []
            try:
                if len(target) != 2:
                    h, p = name.rsplit(":", 1)
                    target = (h, int(p))
                mst, mraw = _http_get((target[0], int(target[1])),
                                      "/metrics/snapshot")
                if mst == 200:
                    snapshots[name] = json.loads(mraw.decode())
            except (OSError, ValueError):
                pass
        report(snapshots, as_json=as_json, status=status)
        return 0
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__)
        return 2
    report(load_snapshots(paths), as_json=as_json)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Chaos harness (ISSUE 2 satellite): train the LeNet example under a
randomized-but-seeded fault-injection plan and assert the final loss
matches an uninjected run.

The determinism argument: the data pipeline is unshuffled, recovery
replays from the last epoch-boundary checkpoint with the exact batch
order, delays change no math, and corrupt checkpoint writes are
quarantined at restore time — so every injected schedule must converge
to the SAME final loss as the clean run. Any divergence means a failure
path dropped or replayed work incorrectly, which is precisely what this
harness exists to catch.

Usage:
    python tools/chaos_check.py [--seed N] [--events K] [--full]
        [--kvcache | --kvtier | --failover | --all]

Wired into ``bench.py``'s telemetry block as a smoke invocation and into
pytest as ``-m chaos`` (kept out of tier-1 by the ``slow`` marker).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional

import numpy as np

# runnable as `python tools/chaos_check.py` from the repo root: the
# script dir is on sys.path then, the package root is not
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _train_once(n: int, epochs: int, batch: int, ckpt_dir: Optional[str],
                max_retry: int = 0) -> float:
    """One deterministic LeNet training run (the examples/lenet_mnist
    model over synthetic digits, unshuffled) → final loss."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.feature.dataset import LocalDataSet
    from bigdl_tpu.models.lenet import build_model
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    set_seed(0)
    rs = np.random.RandomState(0)
    x = rs.rand(n, 1, 28, 28).astype(np.float32)
    y = (rs.randint(0, 10, n) + 1).astype(np.int32)
    model = build_model(10)
    opt = LocalOptimizer(model, LocalDataSet(x, y, shuffle=False),
                         nn.ClassNLLCriterion(), batch_size=batch,
                         end_trigger=Trigger.max_epoch(epochs))
    if ckpt_dir:
        opt.set_checkpoint(ckpt_dir, Trigger.every_epoch())
    if max_retry:
        opt.set_max_retry(max_retry)
    opt.optimize()
    return float(opt.state["loss"])


def run_chaos(seed: int = 0, events: int = 5, smoke: bool = True,
              rtol: float = 1e-4) -> dict:
    """The harness: clean run, then the same run under an armed seeded
    plan (kill/corrupt/delay events over the training+checkpoint sites),
    assert the final losses match. Returns the comparison record."""
    from bigdl_tpu import reliability as rel

    n, epochs, batch = (64, 3, 16) if smoke else (256, 5, 32)
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean = _train_once(n, epochs, batch, ckpt_dir=None)

        # the injected run: faults target the recovery-relevant sites;
        # the retry budget outnumbers the raise events so training
        # always completes; seeded => exactly reproducible
        plan = rel.FaultPlan(seed=seed).randomize(
            events, sites=("optimizer.step", "checkpoint.write",
                           "checkpoint.write.manifest",
                           "checkpoint.commit", "optimizer.checkpoint"))
        with tempfile.TemporaryDirectory() as ckpt_dir:
            rel.set_plan(plan)
            try:
                injected = _train_once(n, epochs, batch,
                                       ckpt_dir=ckpt_dir,
                                       max_retry=events + 1)
            finally:
                rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()   # leave the process how we found it

    match = bool(np.isclose(clean, injected, rtol=rtol, atol=1e-6))
    out = {
        "seed": seed,
        "events_armed": events,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "clean_loss": clean,
        "injected_loss": injected,
        "match": match,
    }
    if not match:
        raise AssertionError(
            f"chaos divergence: clean loss {clean} vs injected "
            f"{injected} (fired: {out['events_fired']})")
    return out


def run_kvcache_chaos(seed: int = 0, n_requests: int = 6,
                      raises: int = 2) -> dict:
    """ISSUE 5 satellite: serve a shared-prefix workload through the
    prefix cache with seeded ``kvcache.evict`` faults armed (delays on
    every eviction to widen race windows, plus a few raises — the site
    fires before any state mutates, so the engine loop retries cleanly)
    and assert greedy outputs are token-identical to the clean cache-on
    run. The pool is sized small so eviction genuinely happens."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, 250, 12).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rs.randint(0, 250, 2 + j % 5)
                               .astype(np.int32)])
               for j in range(n_requests)]

    def serve_all():
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=7, kvcache=True).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
            return ([list(map(int, r.get(timeout=300))) for r in reqs],
                    srv._kv.evictions)
        finally:
            srv.stop()

    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, clean_evicts = serve_all()
        plan = rel.FaultPlan(seed=seed)
        # rules match first-wins: the bounded raises go first (skipping
        # the first call), the unbounded delays mop up every other pass
        plan.add("kvcache.evict", "raise", times=raises, after=1)
        plan.add("kvcache.evict", "delay", times=None, delay=0.002)
        rel.set_plan(plan)
        try:
            injected, injected_evicts = serve_all()
        finally:
            rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()

    match = injected == clean
    out = {
        "seed": seed,
        "requests": n_requests,
        "clean_evictions": clean_evicts,
        "injected_evictions": injected_evicts,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "match": match,
    }
    if not out["events_fired"]:
        raise AssertionError(
            "kvcache chaos armed but no kvcache.evict fault fired — "
            "the pool was not under pressure; shrink it")
    if not match:
        raise AssertionError(
            f"kvcache chaos divergence under eviction faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    return out


def run_kvtier_chaos(seed: int = 0, n_groups: int = 4,
                     fetch_raises: int = 2, spill_raises: int = 1) -> dict:
    """ISSUE 6 satellite: drive spill→reload traffic through the host
    tier with seeded ``kvtier.spill``/``kvtier.fetch`` faults armed —
    delays on every migration to widen the async windows, plus raises
    on both directions — and assert greedy outputs are token-identical
    to the clean tier-on run. The contract under failure: a failed
    spill is a plain eviction, a failed fetch a plain cache miss —
    never a stall, a crash, or a different token."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    groups = [rs.randint(0, 250, 16).astype(np.int32)
              for _ in range(n_groups)]
    prompts = []
    for rnd in range(2):          # two passes: seed chains, then reload
        for g in range(n_groups):
            prompts.append(np.concatenate(
                [groups[g], rs.randint(0, 250, 2 + (rnd + g) % 3)
                 .astype(np.int32)]))

    def serve_all():
        # pool fits ~2 of the 4 chains -> pass 2 must hit the arena
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=9, kvcache=True, kvtier=True,
                        host_pages=32).start()
        try:
            got = [list(map(int,
                            srv.submit(p, max_new_tokens=4)
                            .get(timeout=300)))
                   for p in prompts]
            return got, srv._tier.spills, srv._tier.fetches
        finally:
            srv.stop()

    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, clean_spills, clean_fetches = serve_all()
        plan = rel.FaultPlan(seed=seed)
        # first-match-wins: bounded raises first, unbounded delays mop
        # up every other migration
        plan.add("kvtier.fetch", "raise", times=fetch_raises, after=0)
        plan.add("kvtier.spill", "raise", times=spill_raises, after=1)
        plan.add("kvtier.*", "delay", times=None, delay=0.003)
        rel.set_plan(plan)
        try:
            injected, inj_spills, inj_fetches = serve_all()
        finally:
            rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()

    match = injected == clean
    out = {
        "seed": seed,
        "requests": len(prompts),
        "clean_spills": clean_spills,
        "clean_fetches": clean_fetches,
        "injected_spills": inj_spills,
        "injected_fetches": inj_fetches,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "match": match,
    }
    if clean_fetches == 0:
        raise AssertionError(
            "kvtier chaos: the clean run never fetched from the host "
            "arena — the pool is not under pressure; shrink it")
    if not any(s.startswith("kvtier.") for s, _ in plan.fired):
        raise AssertionError(
            "kvtier chaos armed but no kvtier fault fired")
    if not match:
        raise AssertionError(
            f"kvtier chaos divergence under migration faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    return out


def run_failover_chaos(seed: int = 0, n_requests: int = 4,
                       kills: int = 2, stalls: int = 1,
                       new_tokens: int = 5,
                       smoke: bool = False) -> dict:
    """ISSUE 7 acceptance: a kill storm against the disaggregated
    router must cost latency, not answers. Two decode workers behind a
    failover-enabled ``LLMRouter``; seeded ``router.dispatch`` raises
    tear connections mid-stream (after tokens drained) and seeded
    ``worker.stall`` hangs wedge an engine past its watchdog timeout —
    every request must still complete with greedy output bit-identical
    to ``model.generate``, with the journal resuming
    ``prompt + generated_so_far`` on the surviving backend.

    Also asserts the disabled-mode contract: with failover/hedging off
    the router is structurally the PR 6 object — no journal, no prober
    thread, no ``bigdl_router_failovers/hedges/journal`` metric series
    from serving a request through it.

    ``smoke=True`` shrinks the storm to one kill over two requests
    (dominant costs are the per-shape warmup on both engines and the
    watchdog stall) — the same contract, sized for ``run_all_chaos``
    inside ``bench.py`` telemetry where the full storm's minutes of
    wall-clock would distort a tool people compare numbers across."""
    import threading

    if smoke:
        n_requests = min(n_requests, 2)
        kills = min(kills, 1)
        new_tokens = min(new_tokens, 4)

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    prompts = [rs.randint(0, 250, 10 + 2 * j).astype(np.int32)
               for j in range(n_requests)]
    want = [list(map(int,
                     model.generate(p[None],
                                    max_new_tokens=new_tokens)
                     [0, len(p):]))
            for p in prompts]

    def post(addr, path, body, timeout=600):
        import http.client
        import json as _json
        conn = http.client.HTTPConnection(*addr, timeout=timeout)
        try:
            conn.request("POST", path, _json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    # --- disabled-mode structural absence (cheap, serves one request)
    s0 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8) \
        .start()
    w0 = LLMWorker(s0, role="decode").start()
    before = set(obs.render().splitlines()) if obs.enabled() else set()
    r0 = LLMRouter([], [w0.address], start_prober=False).start()
    try:
        assert r0._journal is None and r0._prober is None \
            and r0._hedge is None, "disabled router built failover state"
        assert not s0.watchdog_enabled and s0._watchdog_thread is None
        st, body = post(r0.address, "/worker_generate",
                        {"prompt_ids": [int(t) for t in prompts[0]],
                         "max_new_tokens": 2})
        assert st == 200, body
        if obs.enabled():
            new = "\n".join(set(obs.render().splitlines()) - before)
            for name in ("bigdl_router_failovers_total",
                         "bigdl_router_hedges_total",
                         "bigdl_router_journal_inflight",
                         "bigdl_router_backend_healthy"):
                assert name not in new, \
                    f"disabled mode grew metric series {name}"
        assert not [t for t in threading.enumerate()
                    if t.name == "bigdl-router-prober"], \
            "disabled mode started a prober thread"
    finally:
        r0.stop()
        w0.stop()
        s0.stop()

    # --- the storm: kills mid-stream + a watchdog-tripping stall
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    # watchdog above the warmed per-step time but under the stall; the
    # engines are warmed below so compiles don't masquerade as stalls
    s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, watchdog_timeout=0.6).start()
    s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, watchdog_timeout=0.6).start()
    w1 = LLMWorker(s1, role="decode").start()
    w2 = LLMWorker(s2, role="decode").start()
    router = LLMRouter([], [w1.address, w2.address], failover=True,
                       failover_attempts=8, start_prober=False).start()
    try:
        # warm EVERY shape the storm will hit on both engines: the
        # first submit compiles the full prefill + decode steps, the
        # second hits the radix index it just seeded and compiles the
        # partial-prefill suffix shape — the same shape every
        # journal resume (prompt + generated, suffix re-prefill) uses.
        # An unwarmed compile stalls the heartbeat exactly like a hung
        # step and would trip the watchdog on the compile instead of
        # the injected stall (see LLMServer._watchdog_loop).
        for srv in (s1, s2):
            for p in prompts:
                srv.submit(p, max_new_tokens=1).get(timeout=600)
                srv.submit(p, max_new_tokens=1).get(timeout=600)
        plan = rel.FaultPlan(seed=seed)
        # mid-stream connection kills: each bounded raise tears the
        # router->worker stream a few drained chunks in (llm.step is
        # slowed so chunks arrive one token at a time, and the
        # dispatch site fires once per drained chunk)
        for k in range(kills):
            plan.add("router.dispatch", "raise", times=1, after=3 + 2 * k)
        # a wedged device step, longer than the 0.6 s watchdog: the
        # victim engine trips mid-generation (the site only fires with
        # live slots), fails its requests retriably, recovers
        plan.add("worker.stall", "delay", times=stalls, after=2,
                 delay=1.5)
        plan.add("llm.step", "delay", times=None, delay=0.02)
        rel.set_plan(plan)
        got = []
        failures = []
        try:
            for j, p in enumerate(prompts):
                st, body = post(router.address, "/worker_generate",
                                {"prompt_ids": [int(t) for t in p],
                                 "max_new_tokens": new_tokens})
                if st != 200:
                    failures.append((j, st, body.get("error")))
                    got.append(None)
                else:
                    got.append(body["output_ids"])
        finally:
            rel.set_plan(None)
            if not was_enabled:
                rel.disable()
        out = {
            "seed": seed,
            "requests": n_requests,
            "events_fired": [f"{s}:{a}" for s, a in plan.fired],
            "failovers": router.failovers,
            "tokens_resumed": router.tokens_resumed,
            "watchdog_trips": s1.watchdog_trips + s2.watchdog_trips,
            "lost_requests": len(failures),
            "match": got == want,
        }
        if failures:
            raise AssertionError(
                f"failover chaos lost {len(failures)} request(s) "
                f"(fired: {out['events_fired']}): {failures}")
        if not any(s == "router.dispatch" for s, _ in plan.fired):
            raise AssertionError(
                "failover chaos armed but no router.dispatch kill "
                "fired — widen the kill windows")
        if router.failovers == 0:
            raise AssertionError(
                "failover chaos completed without a single failover — "
                "the kills landed outside the streams")
        if router.tokens_resumed == 0:
            raise AssertionError(
                "every failover restarted from scratch — no resume "
                "carried drained tokens, so the journal's "
                "suffix-resume path never ran")
        if got != want:
            raise AssertionError(
                f"failover chaos divergence (fired: "
                f"{out['events_fired']}): {got} vs {want}")
        return out
    finally:
        router.stop()
        w1.stop()
        w2.stop()
        s1.stop()
        s2.stop()


def run_all_chaos(seed: int = 0) -> dict:
    """Every chaos suite, one record per pass (the ``chaos_all``
    telemetry block in ``bench.py``). Each pass asserts its own
    parity contract; a failing pass lands as an ``error`` entry
    instead of killing the others."""
    out = {}
    for name, fn in (("train", lambda: run_chaos(seed=seed, events=3,
                                                 smoke=True)),
                     ("kvcache", lambda: run_kvcache_chaos(seed=seed)),
                     ("kvtier", lambda: run_kvtier_chaos(seed=seed)),
                     ("failover", lambda: run_failover_chaos(
                         seed=seed, smoke=True))):
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 — one bad suite
            out[name] = {"error": repr(e)}   # must not hide the rest
    out["ok"] = all("error" not in v for v in out.values()
                    if isinstance(v, dict))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--events", type=int, default=5)
    ap.add_argument("--full", action="store_true",
                    help="bigger model/data than the smoke default")
    ap.add_argument("--kvcache", action="store_true",
                    help="run the kvcache.evict eviction-race pass "
                         "instead of the training chaos run (ISSUE 5)")
    ap.add_argument("--kvtier", action="store_true",
                    help="run the host-tier migration-fault pass: "
                         "delayed/failed spills and fetches must keep "
                         "greedy outputs identical (ISSUE 6)")
    ap.add_argument("--failover", action="store_true",
                    help="run the router kill-storm pass: mid-stream "
                         "decode-worker kills and watchdog-tripping "
                         "engine stalls must lose zero requests with "
                         "greedy outputs bit-identical (ISSUE 7)")
    ap.add_argument("--all", action="store_true",
                    help="run every chaos suite (train, kvcache, "
                         "kvtier, failover) and report one record per "
                         "pass (the bench.py chaos_all block)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (sitecustomize pins the "
                         "axon TPU platform; env vars are ineffective)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.all:
        out = run_all_chaos(seed=args.seed)
        print(json.dumps(out, indent=1))
        if not out["ok"]:
            sys.exit(1)
        return
    if args.failover:
        out = run_failover_chaos(seed=args.seed)
    elif args.kvtier:
        out = run_kvtier_chaos(seed=args.seed)
    elif args.kvcache:
        out = run_kvcache_chaos(seed=args.seed)
    else:
        out = run_chaos(seed=args.seed, events=args.events,
                        smoke=not args.full)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

"""Chaos harness (ISSUE 2 satellite): train the LeNet example under a
randomized-but-seeded fault-injection plan and assert the final loss
matches an uninjected run.

The determinism argument: the data pipeline is unshuffled, recovery
replays from the last epoch-boundary checkpoint with the exact batch
order, delays change no math, and corrupt checkpoint writes are
quarantined at restore time — so every injected schedule must converge
to the SAME final loss as the clean run. Any divergence means a failure
path dropped or replayed work incorrectly, which is precisely what this
harness exists to catch.

Usage:
    python tools/chaos_check.py [--seed N] [--events K] [--full]
        [--kvcache | --kvtier | --failover | --flight | --fleet
         | --preempt | --all]

Wired into ``bench.py``'s telemetry block as a smoke invocation and into
pytest as ``-m chaos`` (kept out of tier-1 by the ``slow`` marker).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import textwrap
from typing import Optional

import numpy as np

# runnable as `python tools/chaos_check.py` from the repo root: the
# script dir is on sys.path then, the package root is not
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _train_once(n: int, epochs: int, batch: int, ckpt_dir: Optional[str],
                max_retry: int = 0) -> float:
    """One deterministic LeNet training run (the examples/lenet_mnist
    model over synthetic digits, unshuffled) → final loss."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.feature.dataset import LocalDataSet
    from bigdl_tpu.models.lenet import build_model
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    set_seed(0)
    rs = np.random.RandomState(0)
    x = rs.rand(n, 1, 28, 28).astype(np.float32)
    y = (rs.randint(0, 10, n) + 1).astype(np.int32)
    model = build_model(10)
    opt = LocalOptimizer(model, LocalDataSet(x, y, shuffle=False),
                         nn.ClassNLLCriterion(), batch_size=batch,
                         end_trigger=Trigger.max_epoch(epochs))
    if ckpt_dir:
        opt.set_checkpoint(ckpt_dir, Trigger.every_epoch())
    if max_retry:
        opt.set_max_retry(max_retry)
    opt.optimize()
    return float(opt.state["loss"])


def run_chaos(seed: int = 0, events: int = 5, smoke: bool = True,
              rtol: float = 1e-4) -> dict:
    """The harness: clean run, then the same run under an armed seeded
    plan (kill/corrupt/delay events over the training+checkpoint sites),
    assert the final losses match. Returns the comparison record."""
    from bigdl_tpu import reliability as rel

    n, epochs, batch = (64, 3, 16) if smoke else (256, 5, 32)
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean = _train_once(n, epochs, batch, ckpt_dir=None)

        # the injected run: faults target the recovery-relevant sites;
        # the retry budget outnumbers the raise events so training
        # always completes; seeded => exactly reproducible
        plan = rel.FaultPlan(seed=seed).randomize(
            events, sites=("optimizer.step", "checkpoint.write",
                           "checkpoint.write.manifest",
                           "checkpoint.commit", "optimizer.checkpoint"))
        with tempfile.TemporaryDirectory() as ckpt_dir:
            rel.set_plan(plan)
            try:
                injected = _train_once(n, epochs, batch,
                                       ckpt_dir=ckpt_dir,
                                       max_retry=events + 1)
            finally:
                rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()   # leave the process how we found it

    match = bool(np.isclose(clean, injected, rtol=rtol, atol=1e-6))
    out = {
        "seed": seed,
        "events_armed": events,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "clean_loss": clean,
        "injected_loss": injected,
        "match": match,
    }
    if not match:
        raise AssertionError(
            f"chaos divergence: clean loss {clean} vs injected "
            f"{injected} (fired: {out['events_fired']})")
    return out


def run_kvcache_chaos(seed: int = 0, n_requests: int = 6,
                      raises: int = 2) -> dict:
    """ISSUE 5 satellite: serve a shared-prefix workload through the
    prefix cache with seeded ``kvcache.evict`` faults armed (delays on
    every eviction to widen race windows, plus a few raises — the site
    fires before any state mutates, so the engine loop retries cleanly)
    and assert greedy outputs are token-identical to the clean cache-on
    run. The pool is sized small so eviction genuinely happens."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, 250, 12).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rs.randint(0, 250, 2 + j % 5)
                               .astype(np.int32)])
               for j in range(n_requests)]

    def serve_all():
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=7, kvcache=True).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
            return ([list(map(int, r.get(timeout=300))) for r in reqs],
                    srv._kv.evictions)
        finally:
            srv.stop()

    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, clean_evicts = serve_all()
        plan = rel.FaultPlan(seed=seed)
        # rules match first-wins: the bounded raises go first (skipping
        # the first call), the unbounded delays mop up every other pass
        plan.add("kvcache.evict", "raise", times=raises, after=1)
        plan.add("kvcache.evict", "delay", times=None, delay=0.002)
        rel.set_plan(plan)
        try:
            injected, injected_evicts = serve_all()
        finally:
            rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()

    match = injected == clean
    out = {
        "seed": seed,
        "requests": n_requests,
        "clean_evictions": clean_evicts,
        "injected_evictions": injected_evicts,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "match": match,
    }
    if not out["events_fired"]:
        raise AssertionError(
            "kvcache chaos armed but no kvcache.evict fault fired — "
            "the pool was not under pressure; shrink it")
    if not match:
        raise AssertionError(
            f"kvcache chaos divergence under eviction faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    return out


def run_kvtier_chaos(seed: int = 0, n_groups: int = 4,
                     fetch_raises: int = 2, spill_raises: int = 1) -> dict:
    """ISSUE 6 satellite: drive spill→reload traffic through the host
    tier with seeded ``kvtier.spill``/``kvtier.fetch`` faults armed —
    delays on every migration to widen the async windows, plus raises
    on both directions — and assert greedy outputs are token-identical
    to the clean tier-on run. The contract under failure: a failed
    spill is a plain eviction, a failed fetch a plain cache miss —
    never a stall, a crash, or a different token."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    groups = [rs.randint(0, 250, 16).astype(np.int32)
              for _ in range(n_groups)]
    prompts = []
    for rnd in range(2):          # two passes: seed chains, then reload
        for g in range(n_groups):
            prompts.append(np.concatenate(
                [groups[g], rs.randint(0, 250, 2 + (rnd + g) % 3)
                 .astype(np.int32)]))

    def serve_all():
        # pool fits ~2 of the 4 chains -> pass 2 must hit the arena
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=9, kvcache=True, kvtier=True,
                        host_pages=32).start()
        try:
            got = [list(map(int,
                            srv.submit(p, max_new_tokens=4)
                            .get(timeout=300)))
                   for p in prompts]
            return got, srv._tier.spills, srv._tier.fetches
        finally:
            srv.stop()

    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, clean_spills, clean_fetches = serve_all()
        plan = rel.FaultPlan(seed=seed)
        # first-match-wins: bounded raises first, unbounded delays mop
        # up every other migration
        plan.add("kvtier.fetch", "raise", times=fetch_raises, after=0)
        plan.add("kvtier.spill", "raise", times=spill_raises, after=1)
        plan.add("kvtier.*", "delay", times=None, delay=0.003)
        rel.set_plan(plan)
        try:
            injected, inj_spills, inj_fetches = serve_all()
        finally:
            rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()

    match = injected == clean
    out = {
        "seed": seed,
        "requests": len(prompts),
        "clean_spills": clean_spills,
        "clean_fetches": clean_fetches,
        "injected_spills": inj_spills,
        "injected_fetches": inj_fetches,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "match": match,
    }
    if clean_fetches == 0:
        raise AssertionError(
            "kvtier chaos: the clean run never fetched from the host "
            "arena — the pool is not under pressure; shrink it")
    if not any(s.startswith("kvtier.") for s, _ in plan.fired):
        raise AssertionError(
            "kvtier chaos armed but no kvtier fault fired")
    if not match:
        raise AssertionError(
            f"kvtier chaos divergence under migration faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    return out


def run_mixed_chaos(seed: int = 0, raises: int = 2) -> dict:
    """ISSUE 14 satellite: drive chunked admissions through the unified
    mixed engine with seeded ``llm.chunk`` faults armed — delays on
    every chunk boundary to widen the interleaving windows, plus raises
    that kill an admission MID-CHAIN. The contract under failure: the
    partial chain's pages and ledger charges roll back completely (the
    idle budget equals the clean run's), the request fails RETRIABLY,
    and a resubmission produces greedy output token-identical to the
    clean run."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, 250, 16).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rs.randint(0, 250, 16 + 8 * (j % 2))
                               .astype(np.int32)])
               for j in range(3)]                      # 32/40-token, chunked
    prompts.append(rs.randint(0, 250, 6).astype(np.int32))   # short

    num_pages = 32

    def serve_all(resubmit: bool):
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=num_pages, kvcache=True, mixed=True,
                        chunk_tokens=8, ragged_prefill=True).start()
        failed = 0
        try:
            reqs = [srv.submit(p, max_new_tokens=4) for p in prompts]
            outs = []
            for j, r in enumerate(reqs):
                try:
                    outs.append(list(map(int, r.get(timeout=300))))
                except RuntimeError as e:
                    if "retriable" not in str(e):
                        raise
                    failed += 1
                    if not resubmit:
                        raise
                    r2 = srv.submit(prompts[j], max_new_tokens=4)
                    outs.append(list(map(int, r2.get(timeout=300))))
        finally:
            srv.stop()
        # read AFTER stop: the drain resolved every deferred fence
        # release, so a nonzero delta is a real ledger leak
        return (outs, failed, srv.prefill_chunks_total,
                srv._budget_avail)

    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, _, clean_chunks, clean_budget = serve_all(resubmit=False)
        plan = rel.FaultPlan(seed=seed)
        # first-match-wins: bounded raises kill admissions mid-chain,
        # the unbounded delays stretch every other chunk boundary
        plan.add("llm.chunk", "raise", times=raises, after=1)
        plan.add("llm.chunk", "delay", times=None, delay=0.002)
        rel.set_plan(plan)
        try:
            injected, failed, inj_chunks, inj_budget = \
                serve_all(resubmit=True)
        finally:
            rel.set_plan(None)
    finally:
        if not was_enabled:
            rel.disable()

    match = injected == clean
    out = {
        "seed": seed,
        "requests": len(prompts),
        "clean_chunks": clean_chunks,
        "injected_chunks": inj_chunks,
        "failed_retriably": failed,
        "clean_idle_budget": clean_budget,
        "injected_idle_budget": inj_budget,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "match": match,
    }
    if clean_chunks == 0:
        raise AssertionError(
            "mixed chaos: the clean run never chunked — prompts are "
            "shorter than chunk_tokens; lengthen them")
    if not any(s == "llm.chunk" for s, _ in plan.fired):
        raise AssertionError(
            "mixed chaos armed but no llm.chunk fault fired")
    if failed == 0:
        raise AssertionError(
            "mixed chaos: no admission failed mid-chain — the raise "
            "rule never landed between chunks")
    if inj_budget != clean_budget or inj_budget != num_pages - 1:
        raise AssertionError(
            f"mixed chaos ledger leak: idle budget {inj_budget} vs "
            f"clean {clean_budget} (pool {num_pages - 1})")
    if not match:
        raise AssertionError(
            f"mixed chaos divergence under chunk faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    return out


def run_spec_chaos(seed: int = 0, raises: int = 2) -> dict:
    """ISSUE 19 satellite: self-speculative decoding under faults.

    A repetitive-suffix workload (so the n-gram proposer genuinely
    drafts) is served twice: spec OFF clean, then spec ON with seeded
    ``llm.spec`` faults armed — the site fires between drafting and
    the verify dispatch, so a raise must degrade that tick to a plain
    decode step, never a wrong token. The contract: greedy outputs
    BIT-IDENTICAL to the spec-off run, the page ledger idle after
    stop (speculative pages release with the slot), and the
    proposed/accepted counters reconciling EXACTLY with the flight
    ``draft``/``verify_accept``/``verify_reject`` events (same call
    sites — any drift is a forked emission path)."""
    import numpy as np

    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.observability import flight
    from bigdl_tpu.utils.conf import conf

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    # the long prompt's pattern is pinned to the seed whose greedy
    # CONTINUATION cycles (what prompt-lookup drafts from is generated
    # history, so acceptance needs the output to repeat) — the fault
    # plan still randomizes on ``seed``
    pattern = np.random.RandomState(42).randint(0, 250, 5) \
        .astype(np.int32)
    rs = np.random.RandomState(seed)
    prompts = [np.tile(pattern, 6).astype(np.int32),
               np.concatenate([pattern,
                               rs.randint(0, 250, 4).astype(np.int32)]),
               rs.randint(0, 250, 9).astype(np.int32)]
    new_tokens = [24, 8, 8]

    num_pages = 24

    def serve_all(sp: bool):
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=num_pages, ragged_prefill=True,
                        spec=sp, spec_k=4).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, new_tokens)]
            outs = [list(map(int, r.get(timeout=300))) for r in reqs]
        finally:
            srv.stop()
        # read AFTER stop: the drain resolved every in-flight verify,
        # so a nonzero delta is a real page leak
        return (outs, srv._budget_avail,
                {"passes": srv.spec_passes,
                 "proposed": srv.spec_proposed_total,
                 "accepted": srv.spec_accepted_total,
                 "emitted": srv.spec_emitted_total})

    def _spec_events():
        r = flight.ring()
        evs = r.events() if r is not None else []
        return {
            "draft": sum(1 for e in evs if e["kind"] == "draft"),
            "drafted": sum(e.get("detail", {}).get("n_draft", 0)
                           for e in evs if e["kind"] == "draft"),
            "verdicts": sum(1 for e in evs
                            if e["kind"] in ("verify_accept",
                                             "verify_reject")),
            "accepted": sum(e.get("detail", {}).get("accepted", 0)
                            for e in evs
                            if e["kind"] in ("verify_accept",
                                             "verify_reject")),
            "dropped": r.dropped if r is not None else 0,
        }

    GATE = "bigdl.observability.flight.enabled"
    with conf._lock:
        prev = conf._set_layer.get(GATE)
    conf.set(GATE, "true")
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    try:
        clean, clean_budget, _ = serve_all(sp=False)
        ev_before = _spec_events()
        c_before = {
            "proposed": _counter_total(
                "bigdl_llm_spec_proposed_tokens_total"),
            "accepted": _counter_total(
                "bigdl_llm_spec_accepted_tokens_total"),
        }
        plan = rel.FaultPlan(seed=seed)
        # first-match-wins: bounded raises kill a speculative tick
        # between the draft and its dispatch (degrade to plain decode),
        # the unbounded delays stretch every other one
        plan.add("llm.spec", "raise", times=raises, after=1)
        plan.add("llm.spec", "delay", times=None, delay=0.002)
        rel.set_plan(plan)
        try:
            injected, inj_budget, stats = serve_all(sp=True)
        finally:
            rel.set_plan(None)
        ev_delta = {k: _spec_events()[k] - ev_before[k]
                    for k in ev_before}
        c_after = {
            "proposed": _counter_total(
                "bigdl_llm_spec_proposed_tokens_total"),
            "accepted": _counter_total(
                "bigdl_llm_spec_accepted_tokens_total"),
        }
    finally:
        rel.set_plan(None)
        if not was_enabled:
            rel.disable()
        if prev is None:
            conf.unset(GATE)
        else:
            conf.set(GATE, prev)

    match = injected == clean
    out = {
        "seed": seed,
        "requests": len(prompts),
        "spec_passes": stats["passes"],
        "proposed": stats["proposed"],
        "accepted": stats["accepted"],
        "clean_idle_budget": clean_budget,
        "injected_idle_budget": inj_budget,
        "events_fired": [f"{s}:{a}" for s, a in plan.fired],
        "flight_events": ev_delta,
        "match": match,
    }
    if stats["passes"] == 0 or stats["accepted"] == 0:
        raise AssertionError(
            "spec chaos: the spec-on run never speculated (or never "
            "accepted a draft) — the workload's continuation is not "
            "repetitive enough, so the reconciliation is vacuous")
    if not any(s == "llm.spec" for s, _ in plan.fired):
        raise AssertionError(
            "spec chaos armed but no llm.spec fault fired")
    if inj_budget != clean_budget or inj_budget != num_pages - 1:
        raise AssertionError(
            f"spec chaos page leak: idle budget {inj_budget} vs clean "
            f"{clean_budget} (pool {num_pages - 1})")
    if not match:
        raise AssertionError(
            f"spec chaos divergence under llm.spec faults "
            f"(fired: {out['events_fired']}): {clean} vs {injected}")
    if ev_delta["dropped"]:
        raise AssertionError(
            "flight ring dropped events mid-check; raise "
            "bigdl.observability.flight.capacity")
    # the reconciliation: EXACT — the events are emitted at the same
    # call sites as the counter increments and the plain-int ledgers
    if ev_delta["draft"] != stats["passes"] \
            or ev_delta["verdicts"] != stats["passes"]:
        raise AssertionError(
            f"flight draft/verdict events ({ev_delta['draft']}/"
            f"{ev_delta['verdicts']}) != {stats['passes']} spec passes")
    if ev_delta["drafted"] != stats["proposed"] \
            or ev_delta["accepted"] != stats["accepted"]:
        raise AssertionError(
            f"flight drafted/accepted token tallies {ev_delta} != "
            f"engine ledgers {stats}")
    if c_before["proposed"] is not None:
        for key in ("proposed", "accepted"):
            got = c_after[key] - c_before[key]
            if got != stats[key]:
                raise AssertionError(
                    f"bigdl_llm_spec_{key}_tokens_total delta ({got}) "
                    f"!= engine ledger ({stats[key]})")
        out["counters_reconciled"] = True
    else:
        out["counters_reconciled"] = "obs disabled: ledger-only"
    return out


def run_failover_chaos(seed: int = 0, n_requests: int = 4,
                       kills: int = 2, stalls: int = 1,
                       new_tokens: int = 5,
                       smoke: bool = False) -> dict:
    """ISSUE 7 acceptance: a kill storm against the disaggregated
    router must cost latency, not answers. Two decode workers behind a
    failover-enabled ``LLMRouter``; seeded ``router.dispatch`` raises
    tear connections mid-stream (after tokens drained) and seeded
    ``worker.stall`` hangs wedge an engine past its watchdog timeout —
    every request must still complete with greedy output bit-identical
    to ``model.generate``, with the journal resuming
    ``prompt + generated_so_far`` on the surviving backend.

    Also asserts the disabled-mode contract: with failover/hedging off
    the router is structurally the PR 6 object — no journal, no prober
    thread, no ``bigdl_router_failovers/hedges/journal`` metric series
    from serving a request through it.

    ``smoke=True`` shrinks the storm to one kill over two requests
    (dominant costs are the per-shape warmup on both engines and the
    watchdog stall) — the same contract, sized for ``run_all_chaos``
    inside ``bench.py`` telemetry where the full storm's minutes of
    wall-clock would distort a tool people compare numbers across."""
    import threading

    if smoke:
        n_requests = min(n_requests, 2)
        kills = min(kills, 1)
        new_tokens = min(new_tokens, 4)

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    prompts = [rs.randint(0, 250, 10 + 2 * j).astype(np.int32)
               for j in range(n_requests)]
    want = [list(map(int,
                     model.generate(p[None],
                                    max_new_tokens=new_tokens)
                     [0, len(p):]))
            for p in prompts]

    def post(addr, path, body, timeout=600):
        import http.client
        import json as _json
        conn = http.client.HTTPConnection(*addr, timeout=timeout)
        try:
            conn.request("POST", path, _json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    # --- disabled-mode structural absence (cheap, serves one request)
    s0 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8) \
        .start()
    w0 = LLMWorker(s0, role="decode").start()
    before = set(obs.render().splitlines()) if obs.enabled() else set()
    r0 = LLMRouter([], [w0.address], start_prober=False).start()
    try:
        assert r0._journal is None and r0._prober is None \
            and r0._hedge is None, "disabled router built failover state"
        assert not s0.watchdog_enabled and s0._watchdog_thread is None
        st, body = post(r0.address, "/worker_generate",
                        {"prompt_ids": [int(t) for t in prompts[0]],
                         "max_new_tokens": 2})
        assert st == 200, body
        if obs.enabled():
            new = "\n".join(set(obs.render().splitlines()) - before)
            for name in ("bigdl_router_failovers_total",
                         "bigdl_router_hedges_total",
                         "bigdl_router_journal_inflight",
                         "bigdl_router_backend_healthy",
                         # ISSUE 12: SLO sketches and classification
                         # series must be structurally absent too
                         "bigdl_llm_ttft_seconds",
                         "bigdl_llm_itl_seconds",
                         "bigdl_router_ttft_seconds",
                         "bigdl_router_itl_seconds",
                         "bigdl_slo_requests_total",
                         "bigdl_slo_burn_rate"):
                assert name not in new, \
                    f"disabled mode grew metric series {name}"
        assert s0._slo is None and r0._slo is None, \
            "disabled mode built an SLO account"
        assert r0._collector is None, \
            "disabled mode built a federation collector"
        assert not [t for t in threading.enumerate()
                    if t.name in ("bigdl-router-prober",
                                  "bigdl-federation-collector")], \
            "disabled mode started a prober/collector thread"
    finally:
        r0.stop()
        w0.stop()
        s0.stop()

    # --- the storm: kills mid-stream + a watchdog-tripping stall
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    # watchdog above the warmed per-step time but under the stall; the
    # engines are warmed below so compiles don't masquerade as stalls
    # SLO accounting rides the storm (ISSUE 12): the counters and the
    # router's token-arrival sketches must survive mid-stream failover
    # with resumed tokens counted exactly once
    s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, watchdog_timeout=0.6, slo=True).start()
    s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, watchdog_timeout=0.6, slo=True).start()
    w1 = LLMWorker(s1, role="decode").start()
    w2 = LLMWorker(s2, role="decode").start()
    router = LLMRouter([], [w1.address, w2.address], failover=True,
                       failover_attempts=8, start_prober=False,
                       slo=True).start()
    # sketch/counter state BEFORE the storm: the registry is process-
    # global (bench's chaos_all runs several suites), so every SLO
    # assertion below is on the delta
    def _slo_counts():
        if not obs.enabled():
            return None
        reg = obs.REGISTRY
        classified = sum(
            reg.sample_value("bigdl_slo_requests_total", slo="ttft",
                             verdict=v, scope="router") or 0.0
            for v in ("ok", "violated"))
        return {
            "ttft": reg.sample_value("bigdl_router_ttft_seconds") or 0.0,
            "itl": reg.sample_value("bigdl_router_itl_seconds") or 0.0,
            "classified": classified}
    slo_before = _slo_counts()
    try:
        # warm EVERY shape the storm will hit on both engines: the
        # first submit compiles the full prefill + decode steps, the
        # second hits the radix index it just seeded and compiles the
        # partial-prefill suffix shape — the same shape every
        # journal resume (prompt + generated, suffix re-prefill) uses.
        # An unwarmed compile stalls the heartbeat exactly like a hung
        # step and would trip the watchdog on the compile instead of
        # the injected stall (see LLMServer._watchdog_loop).
        for srv in (s1, s2):
            for p in prompts:
                srv.submit(p, max_new_tokens=1).get(timeout=600)
                srv.submit(p, max_new_tokens=1).get(timeout=600)
        plan = rel.FaultPlan(seed=seed)
        # mid-stream connection kills: each bounded raise tears the
        # router->worker stream a few drained chunks in (llm.step is
        # slowed so chunks arrive one token at a time, and the
        # dispatch site fires once per drained chunk)
        for k in range(kills):
            plan.add("router.dispatch", "raise", times=1, after=3 + 2 * k)
        # a wedged device step, longer than the 0.6 s watchdog: the
        # victim engine trips mid-generation (the site only fires with
        # live slots), fails its requests retriably, recovers
        plan.add("worker.stall", "delay", times=stalls, after=2,
                 delay=1.5)
        plan.add("llm.step", "delay", times=None, delay=0.02)
        rel.set_plan(plan)
        got = []
        failures = []
        try:
            for j, p in enumerate(prompts):
                st, body = post(router.address, "/worker_generate",
                                {"prompt_ids": [int(t) for t in p],
                                 "max_new_tokens": new_tokens})
                if st != 200:
                    failures.append((j, st, body.get("error")))
                    got.append(None)
                else:
                    got.append(body["output_ids"])
        finally:
            rel.set_plan(None)
            if not was_enabled:
                rel.disable()
        out = {
            "seed": seed,
            "requests": n_requests,
            "events_fired": [f"{s}:{a}" for s, a in plan.fired],
            "failovers": router.failovers,
            "tokens_resumed": router.tokens_resumed,
            "watchdog_trips": s1.watchdog_trips + s2.watchdog_trips,
            "lost_requests": len(failures),
            "match": got == want,
        }
        if failures:
            raise AssertionError(
                f"failover chaos lost {len(failures)} request(s) "
                f"(fired: {out['events_fired']}): {failures}")
        if not any(s == "router.dispatch" for s, _ in plan.fired):
            raise AssertionError(
                "failover chaos armed but no router.dispatch kill "
                "fired — widen the kill windows")
        if router.failovers == 0:
            raise AssertionError(
                "failover chaos completed without a single failover — "
                "the kills landed outside the streams")
        if router.tokens_resumed == 0:
            raise AssertionError(
                "every failover restarted from scratch — no resume "
                "carried drained tokens, so the journal's "
                "suffix-resume path never ran")
        if got != want:
            raise AssertionError(
                f"failover chaos divergence (fired: "
                f"{out['events_fired']}): {got} vs {want}")
        # ISSUE 12: SLO accounting survived the storm. Each of the
        # n_requests classified exactly once; the router's ITL sketch
        # holds exactly (tokens - 1) samples per request — a resume
        # that double-stamped its replayed prefix would inflate this,
        # a resume that dropped stamps would deflate it.
        slo_after = _slo_counts()
        if slo_after is not None:
            ttft_n = slo_after["ttft"] - slo_before["ttft"]
            itl_n = slo_after["itl"] - slo_before["itl"]
            cls_n = slo_after["classified"] - slo_before["classified"]
            want_itl = sum(len(w) - 1 for w in want)
            out["slo_ttft_samples"] = ttft_n
            out["slo_itl_samples"] = itl_n
            if ttft_n != len(want):
                raise AssertionError(
                    f"SLO ttft sketch holds {ttft_n} samples for "
                    f"{len(want)} requests — failover double- or "
                    "under-counted first tokens")
            if itl_n != want_itl:
                raise AssertionError(
                    f"SLO itl sketch holds {itl_n} samples, expected "
                    f"{want_itl} (tokens-1 per request): resumed "
                    "tokens were not counted exactly once")
            if cls_n != len(want):
                raise AssertionError(
                    f"bigdl_slo_requests_total classified {cls_n} "
                    f"requests, expected {len(want)}")
        return out
    finally:
        router.stop()
        w1.stop()
        w2.stop()
        s1.stop()
        s2.stop()


def run_api_chaos(seed: int = 0, n_requests: int = 3, kills: int = 1,
                  new_tokens: int = 5, smoke: bool = False) -> dict:
    """ISSUE 20 acceptance: the OpenAI gateway's SSE stream rides the
    failover journal, so a mid-stream ``router.dispatch`` kill under a
    live SSE client must be invisible at the ``data:`` boundary — the
    concatenated stream stays bit-identical to ``model.generate`` and
    every relayed token is stamped exactly once in the router's SLO
    sketches (the chunks and the stamps fire from the same journal
    drain event — one accounting, not two).

    Also asserts the disabled-mode contract: with the gate off the
    worker and router hold no gateway object, ``/v1/*`` answers 404
    naming ``bigdl.llm.api.enabled``, and serving a native request
    grows no ``bigdl_api_*`` metric series."""
    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
    from tools.loadgen import _post_stream_openai

    if smoke:
        n_requests = min(n_requests, 2)
        new_tokens = min(new_tokens, 4)

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    prompts = [rs.randint(0, 250, 8 + 2 * j).astype(np.int32)
               for j in range(n_requests)]
    want = [list(map(int,
                     model.generate(p[None],
                                    max_new_tokens=new_tokens)
                     [0, len(p):]))
            for p in prompts]

    def get(addr, path, timeout=60):
        import http.client
        import json as _json
        conn = http.client.HTTPConnection(*addr, timeout=timeout)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    # --- disabled-mode structural absence (gate off, one native req)
    s0 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8) \
        .start()
    w0 = LLMWorker(s0, role="decode").start()
    r0 = LLMRouter([], [w0.address], failover=True,
                   start_prober=False).start()
    before = set(obs.render().splitlines()) if obs.enabled() else set()
    try:
        assert w0._api is None and r0._api is None, \
            "disabled mode built a gateway object"
        for addr in (w0.address, r0.address):
            st, body = get(addr, "/v1/models")
            assert st == 404 and \
                "bigdl.llm.api.enabled" in body.get("error", ""), \
                f"disabled /v1/models answered {st}: {body}"
        st, body = _post_stream_openai(
            w0.address, {"prompt_ids": [1, 2, 3],
                         "max_new_tokens": 2}, 60)[:2]
        assert st == 404 and \
            "bigdl.llm.api.enabled" in body.get("error", ""), \
            f"disabled /v1/completions answered {st}: {body}"
        srv_out = s0.submit(prompts[0], max_new_tokens=2).get(
            timeout=600)
        assert len(srv_out) == 2, f"warmup answered {srv_out!r}"
        if obs.enabled():
            new = "\n".join(set(obs.render().splitlines()) - before)
            assert "bigdl_api_" not in new, \
                f"disabled mode grew gateway series: {new}"
    finally:
        r0.stop()
        w0.stop()
        s0.stop()

    # --- the storm: SSE client + mid-stream dispatch kill
    was_enabled = rel.enabled()
    if not was_enabled:
        rel.enable()
    s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, slo=True).start()
    s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                   kvcache=True, slo=True).start()
    w1 = LLMWorker(s1, role="decode").start()
    w2 = LLMWorker(s2, role="decode").start()
    router = LLMRouter([], [w1.address, w2.address], failover=True,
                       failover_attempts=8, start_prober=False,
                       slo=True, api=True).start()

    def _slo_counts():
        if not obs.enabled():
            return None
        reg = obs.REGISTRY
        return {
            "ttft": reg.sample_value("bigdl_router_ttft_seconds") or 0.0,
            "itl": reg.sample_value("bigdl_router_itl_seconds") or 0.0}
    slo_before = _slo_counts()
    try:
        # warm every storm shape on both engines (prefill + suffix
        # resume) so compiles don't eat the kill windows
        for srv in (s1, s2):
            for p in prompts:
                srv.submit(p, max_new_tokens=1).get(timeout=600)
                srv.submit(p, max_new_tokens=1).get(timeout=600)
        plan = rel.FaultPlan(seed=seed)
        for k in range(kills):
            plan.add("router.dispatch", "raise", times=1,
                     after=3 + 2 * k)
        plan.add("llm.step", "delay", times=None, delay=0.02)
        rel.set_plan(plan)
        got = []
        failures = []
        try:
            for j, p in enumerate(prompts):
                st, parsed, _, ttft, gaps = _post_stream_openai(
                    router.address,
                    {"prompt_ids": [int(t) for t in p],
                     "max_new_tokens": new_tokens}, 600)
                if st != 200 or parsed.get("error") is not None:
                    failures.append((j, st, parsed.get("error")))
                    got.append(None)
                else:
                    got.append(parsed["output_ids"])
        finally:
            rel.set_plan(None)
            if not was_enabled:
                rel.disable()
        out = {
            "seed": seed,
            "requests": n_requests,
            "events_fired": [f"{s}:{a}" for s, a in plan.fired],
            "failovers": router.failovers,
            "tokens_resumed": router.tokens_resumed,
            "lost_requests": len(failures),
            "match": got == want,
        }
        if failures:
            raise AssertionError(
                f"api chaos lost {len(failures)} request(s) "
                f"(fired: {out['events_fired']}): {failures}")
        if not any(s == "router.dispatch" for s, _ in plan.fired):
            raise AssertionError(
                "api chaos armed but no router.dispatch kill fired — "
                "widen the kill windows")
        if router.failovers == 0:
            raise AssertionError(
                "api chaos completed without a failover — the kill "
                "landed outside the SSE-relayed stream")
        if got != want:
            raise AssertionError(
                f"SSE stream divergence (fired: {out['events_fired']}"
                f"): {got} vs {want}")
        # the SSE boundary and the SLO sketches are ONE accounting:
        # exactly n first-token stamps and Σ(tokens-1) gap stamps for
        # the streamed requests, failover or not
        slo_after = _slo_counts()
        if slo_after is not None:
            ttft_n = slo_after["ttft"] - slo_before["ttft"]
            itl_n = slo_after["itl"] - slo_before["itl"]
            want_itl = sum(len(w) - 1 for w in want)
            out["slo_ttft_samples"] = ttft_n
            out["slo_itl_samples"] = itl_n
            if ttft_n != len(want):
                raise AssertionError(
                    f"SLO ttft sketch holds {ttft_n} samples for "
                    f"{len(want)} SSE requests — the relay double- or "
                    "under-stamped first tokens")
            if itl_n != want_itl:
                raise AssertionError(
                    f"SLO itl sketch holds {itl_n} samples, expected "
                    f"{want_itl}: SSE-relayed tokens were not stamped "
                    "exactly once")
        return out
    finally:
        router.stop()
        w1.stop()
        w2.stop()
        s1.stop()
        s2.stop()


def _counter_total(name: str) -> Optional[float]:
    """Sum of every child of one registry counter, or None when the
    observability registry is disabled (the flight cross-check then
    reconciles against the plain-int ledgers instead)."""
    from bigdl_tpu import observability as obs
    if not obs.enabled():
        return None
    total = 0.0
    for m in obs.REGISTRY.collect():
        if m.name == name:
            for _key, child in m.children():
                total += child.value
    return total


def _flight_tally() -> dict:
    """Flight-ring totals the reconciliation diffs: shed/failover event
    counts, Σ(evict event pages), and the ring's drop counter (a drop
    between the before/after snapshots would invalidate the diff)."""
    from bigdl_tpu.observability import flight
    r = flight.ring()
    evs = r.events() if r is not None else []
    return {
        "shed": sum(1 for e in evs if e["kind"] == "shed"),
        "failover": sum(1 for e in evs if e["kind"] == "failover"),
        "evict_pages": sum(e.get("detail", {}).get("pages", 0)
                           for e in evs if e["kind"] == "evict"),
        "dropped": r.dropped if r is not None else 0,
    }


def run_flight_chaos(seed: int = 0, new_tokens: int = 4,
                     smoke: bool = False) -> dict:
    """ISSUE 16 acceptance: the flight recorder under a failover storm.

    Part 1 — disabled mode is STRUCTURALLY absent. With
    ``bigdl.observability.flight.enabled`` off, ``flight.record`` is a
    no-op (the ring does not grow, the ``bigdl_flight_events_total``
    counter does not move, no new metric series appears in the
    registry) and both debug endpoints answer 404.

    Part 2 — with the recorder ON, a kill storm + pool-pressure replay
    + drain sheds, then the reconciliation: flight ``shed`` /
    ``failover`` events and Σ(``evict`` event pages) must match the
    ``bigdl_reliability_shed_total`` / ``bigdl_router_failovers_total``
    / ``bigdl_kvcache_evictions_total`` counter deltas EXACTLY. The
    events are emitted at the same call sites as the counter
    increments, so any drift means a forked emission path."""
    import http.client
    import json as _json

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
    from bigdl_tpu.observability import flight
    from bigdl_tpu.utils.conf import conf

    GATE = "bigdl.observability.flight.enabled"
    with conf._lock:
        prev = conf._set_layer.get(GATE)

    out = {"seed": seed, "gate": GATE}
    try:
        # --- part 1: disabled mode is structurally absent ---------------
        conf.set(GATE, "false")
        assert not flight.enabled, f"{GATE}=false left the recorder armed"
        before = _flight_tally()
        lines_before = (set(obs.render().splitlines())
                        if obs.enabled() else set())
        counter_before = _counter_total("bigdl_flight_events_total")
        flight.record("shed", request_id="chaos-probe",
                      component="chaos_probe")
        flight.record("evict", pages=3)
        for path in ("/debug/flight", "/debug/explain/chaos-probe"):
            resp = flight.debug_endpoint(path)
            assert resp is not None and resp[0] == 404, \
                f"{path} must 404 while {GATE} is off, got {resp!r}"
        after = _flight_tally()
        assert after == before, \
            f"record() grew the ring while {GATE} was off: {after}"
        assert _counter_total("bigdl_flight_events_total") \
            == counter_before, \
            f"bigdl_flight_events_total moved while {GATE} was off"
        if obs.enabled():
            grown = {ln.split("{")[0].split(" ")[0]
                     for ln in set(obs.render().splitlines())
                     - lines_before}
            assert not any("flight" in g for g in grown), \
                f"disabled mode grew flight series: {grown}"
        out["disabled_mode"] = "structurally absent"

        # --- part 2: the storm, recorder on -----------------------------
        conf.set(GATE, "true")
        assert flight.enabled
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=128)
        rs = np.random.RandomState(seed)
        storm_prompts = [rs.randint(0, 250, 10 + 2 * j).astype(np.int32)
                         for j in range(2)]
        shared = rs.randint(0, 250, 12).astype(np.int32)
        evict_prompts = [np.concatenate(
            [shared, rs.randint(0, 250, 2 + j % 5).astype(np.int32)])
            for j in range(3 if smoke else 6)]

        was_enabled = rel.enabled()
        if not was_enabled:
            rel.enable()
        # small pool (the kvcache pass's sizing) so the shared-prefix
        # replay genuinely evicts; kills tear the router->worker stream
        # mid-decode so the journal resume path genuinely fires
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       num_pages=7, kvcache=True).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       num_pages=7, kvcache=True).start()
        w1 = LLMWorker(s1, role="decode").start()
        w2 = LLMWorker(s2, role="decode").start()
        router = LLMRouter([], [w1.address, w2.address], failover=True,
                           failover_attempts=8, start_prober=False) \
            .start()
        try:
            # warm the storm shapes on both engines (resume re-prefills
            # prompt+generated through the partial-prefill shape)
            for srv in (s1, s2):
                for p in storm_prompts:
                    srv.submit(p, max_new_tokens=1).get(timeout=600)
                    srv.submit(p, max_new_tokens=1).get(timeout=600)

            t_before = _flight_tally()
            c_before = {
                "shed": _counter_total("bigdl_reliability_shed_total"),
                "failover": _counter_total(
                    "bigdl_router_failovers_total"),
                "evict": _counter_total(
                    "bigdl_kvcache_evictions_total"),
            }
            fo_before = router.failovers
            ev_before = s1._kv.evictions + s2._kv.evictions

            plan = rel.FaultPlan(seed=seed)
            plan.add("router.dispatch", "raise", times=1, after=3)
            plan.add("llm.step", "delay", times=None, delay=0.02)
            rel.set_plan(plan)
            try:
                for p in storm_prompts:
                    conn = http.client.HTTPConnection(*router.address,
                                                      timeout=600)
                    try:
                        conn.request(
                            "POST", "/worker_generate",
                            _json.dumps({
                                "prompt_ids": [int(t) for t in p],
                                "max_new_tokens": new_tokens}),
                            {"Content-Type": "application/json"})
                        r = conn.getresponse()
                        body = _json.loads(r.read().decode())
                        assert r.status == 200, body
                    finally:
                        conn.close()
            finally:
                rel.set_plan(None)
            # pool-pressure replay: shared-prefix chains past the
            # 7-page pool force radix evictions (flight "evict" events)
            reqs = [s1.submit(p, max_new_tokens=new_tokens)
                    for p in evict_prompts]
            for r in reqs:
                r.get(timeout=600)
            # drain sheds: begin_drain flips the admission arm that
            # emits the shed event + counter at one shared site
            s1.begin_drain()
            sheds_forced = 0
            for p in storm_prompts:
                try:
                    s1.submit(p, max_new_tokens=1)
                except rel.OverloadError:
                    sheds_forced += 1
            s1.cancel_drain()
            assert sheds_forced == len(storm_prompts), \
                "draining engine accepted a submit"

            # one live HTTP probe: the worker surface serves the ring
            conn = http.client.HTTPConnection(*w1.address, timeout=60)
            try:
                conn.request("GET", "/debug/flight?kind=evict")
                r = conn.getresponse()
                ring_doc = _json.loads(r.read().decode())
                assert r.status == 200, ring_doc
                assert ring_doc["events"], \
                    "GET /debug/flight?kind=evict returned no events"
            finally:
                conn.close()

            t_after = _flight_tally()
            c_after = {
                "shed": _counter_total("bigdl_reliability_shed_total"),
                "failover": _counter_total(
                    "bigdl_router_failovers_total"),
                "evict": _counter_total(
                    "bigdl_kvcache_evictions_total"),
            }
            fo_delta = router.failovers - fo_before
            ev_delta = s1._kv.evictions + s2._kv.evictions - ev_before
            assert t_after["dropped"] == t_before["dropped"], \
                "ring dropped events mid-check; raise " \
                "bigdl.observability.flight.capacity"
            deltas = {k: t_after[k] - t_before[k]
                      for k in ("shed", "failover", "evict_pages")}
            out.update(events=deltas, failovers=fo_delta,
                       evicted_pages=ev_delta,
                       events_fired=[f"{s}:{a}" for s, a in plan.fired])
            if fo_delta == 0:
                raise AssertionError(
                    "flight chaos storm completed without a failover — "
                    "the kill landed outside the streams")
            if ev_delta == 0:
                raise AssertionError(
                    "flight chaos replay forced no evictions — the "
                    "pool was not under pressure; shrink it")
            # the reconciliation: EXACT, no tolerance — shared call
            # sites mean any drift is a forked emission path
            if deltas["failover"] != fo_delta:
                raise AssertionError(
                    f"{deltas['failover']} flight failover events vs "
                    f"{fo_delta} journal failovers")
            if deltas["evict_pages"] != ev_delta:
                raise AssertionError(
                    f"flight evict events carry {deltas['evict_pages']} "
                    f"pages vs {ev_delta} ledger evictions")
            if deltas["shed"] < sheds_forced:
                raise AssertionError(
                    f"{sheds_forced} sheds forced but only "
                    f"{deltas['shed']} flight shed events recorded")
            if c_before["shed"] is not None:
                for key, counter in (("shed", "shed"),
                                     ("failover", "failover"),
                                     ("evict_pages", "evict")):
                    got = c_after[counter] - c_before[counter]
                    if deltas[key] != got:
                        raise AssertionError(
                            f"flight {key} events ({deltas[key]}) != "
                            f"bigdl_*_total counter delta ({got})")
                out["counters_reconciled"] = True
            else:
                out["counters_reconciled"] = "obs disabled: ledger-only"
        finally:
            rel.set_plan(None)
            if not was_enabled:
                rel.disable()
            router.stop()
            w1.stop()
            w2.stop()
            s1.stop()
            s2.stop()
    finally:
        if prev is None:
            conf.unset(GATE)
        else:
            conf.set(GATE, prev)
    out["match"] = True
    return out


def run_alerts_chaos(seed: int = 0, new_tokens: int = 3,
                     smoke: bool = False) -> dict:
    """ISSUE 18 acceptance: the time-series plane + alert engine under
    a seeded failover storm.

    Part 1 — disabled mode is STRUCTURALLY absent. With
    ``bigdl.observability.timeseries.enabled`` off, ``acquire()``
    builds nothing, no sampler thread exists, no
    ``bigdl_timeseries_*`` / ``bigdl_alerts_*`` series appears, and
    ``/metrics/query``, ``/fleet/timeline`` and ``/alerts`` all answer
    404 naming the gate key.

    Part 2 — plane ON with a tiny-window fast-burn rule installed
    through the declarative ``bigdl.observability.alerts.rules`` path:
    clean traffic keeps the rule inactive; a seeded failover storm
    (mid-stream ``router.dispatch`` kill + ``llm.step`` delays pushing
    every request past the TTFT objective) must flip it to firing on
    the FIRST store sample after the storm (one evaluation interval),
    hold firing while the storm is still inside both windows, and
    resolve once the windows drain past it under clean recovery
    traffic. Alert state transitions must reconcile EXACTLY with the
    flight ``alert_fire`` / ``alert_resolve`` events (same call site)
    and with the ``bigdl_alerts_transitions_total`` counter deltas.

    Part 3 — the autoscaler reads its shed-pressure signal through the
    store's :class:`~bigdl_tpu.observability.timeseries.WindowedCounter`
    primitive now; replaying the OLD summed-delta formula over the
    controller's recorded ``sheds_by`` traces must yield the identical
    pressure/idle/action sequence on restart-free traces (the
    per-member primitive only diverges where the old clamp was wrong:
    a member restart no longer swallows the other members' sheds)."""
    import http.client
    import json as _json
    import threading
    from urllib.parse import quote

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.fleet import FleetController
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
    from bigdl_tpu.observability import alerts, flight
    from bigdl_tpu.observability import timeseries as ts
    from bigdl_tpu.utils.conf import conf

    GATE = "bigdl.observability.timeseries.enabled"
    KEYS = (GATE, "bigdl.observability.timeseries.interval",
            "bigdl.observability.alerts.rules",
            "bigdl.observability.flight.enabled")
    with conf._lock:
        prev = {k: conf._set_layer.get(k) for k in KEYS}

    def post(addr, path, body, timeout=600):
        conn = http.client.HTTPConnection(*addr, timeout=timeout)
        try:
            conn.request("POST", path, _json.dumps(body),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    def get(addr, path, timeout=60):
        conn = http.client.HTTPConnection(*addr, timeout=timeout)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    def _alert_events():
        r = flight.ring()
        evs = r.events() if r is not None else []
        return {"fire": sum(1 for e in evs if e["kind"] == "alert_fire"),
                "resolve": sum(1 for e in evs
                               if e["kind"] == "alert_resolve")}

    RULE = "chaos-fast-burn-ttft"

    def _trans(state):
        if not obs.enabled():
            return 0.0
        return obs.REGISTRY.sample_value(
            "bigdl_alerts_transitions_total", rule=RULE,
            state=state) or 0.0

    out = {"seed": seed, "gate": GATE}
    try:
        # --- part 1: disabled mode is structurally absent ---------------
        conf.set(GATE, "false")
        assert not ts.enabled, f"{GATE}=false left the plane armed"
        lines_before = (set(obs.render().splitlines())
                        if obs.enabled() else set())
        assert ts.acquire() is None, \
            "acquire() built a store while the gate was off"
        for path in ("/metrics/query?series=bigdl_slo_requests_total"
                     "&window=60",
                     "/fleet/timeline?series=bigdl_slo_requests_total"):
            resp = ts.debug_endpoint(path)
            assert resp is not None and resp[0] == 404 \
                and resp[1].get("gate") == GATE, \
                f"{path} must 404 naming {GATE} while off, got {resp!r}"
        resp = alerts.debug_endpoint("/alerts")
        assert resp is not None and resp[0] == 404 \
            and resp[1].get("gate") == GATE, \
            f"/alerts must 404 naming {GATE} while off, got {resp!r}"
        assert not [t for t in threading.enumerate()
                    if t.name == ts.TimeSeriesStore.THREAD_NAME], \
            "disabled mode has a live sampler thread"
        if obs.enabled():
            grown = set(obs.render().splitlines()) - lines_before
            leaked = [g for g in grown
                      if "bigdl_timeseries" in g or "bigdl_alerts" in g]
            assert not leaked, \
                f"disabled mode grew time-series series: {leaked}"
        out["disabled_mode"] = "structurally absent"

        # --- part 2: the storm, plane + alert engine on -----------------
        conf.set(GATE, "true")
        # park the wall-clock sampler: every sample below is a manual
        # fake-clock tick, and a stray real-time sample (ts ~ 1.7e9)
        # would evict the whole fake-ts ring through retention
        conf.set("bigdl.observability.timeseries.interval", "3600")
        conf.set("bigdl.observability.flight.enabled", "true")
        rules = [{"name": RULE, "kind": "burn_rate", "slo": "ttft",
                  "short": 6.0, "long": 12.0, "factor": 5.0}]
        conf.set("bigdl.observability.alerts.rules", _json.dumps(rules))
        assert ts.enabled

        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=128)
        rs = np.random.RandomState(seed)
        n_storm = 2 if smoke else 3
        prompts = [rs.randint(0, 250, 10 + 2 * j).astype(np.int32)
                   for j in range(n_storm)]

        was_enabled = rel.enabled()
        if not was_enabled:
            rel.enable()
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True, slo=True).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True, slo=True).start()
        w1 = LLMWorker(s1, role="decode").start()
        w2 = LLMWorker(s2, role="decode").start()
        router = LLMRouter([], [w1.address, w2.address], failover=True,
                           failover_attempts=8, start_prober=False,
                           slo=True).start()
        try:
            st = ts.store()
            eng = alerts.engine()
            assert st is not None and eng is not None, \
                "plane on but acquire() built no store/engine"
            assert [r["name"] for r in eng.rules] == [RULE], \
                "declarative rules override did not replace built-ins"
            assert [t for t in threading.enumerate()
                    if t.name == ts.TimeSeriesStore.THREAD_NAME], \
                "plane on but no sampler thread"

            # warm every storm shape on both engines (resume re-prefills
            # through the partial-prefill shape; an unwarmed compile
            # would smear real seconds into the TTFT the storm asserts)
            for srv in (s1, s2):
                for p in prompts:
                    srv.submit(p, max_new_tokens=1).get(timeout=600)
                    srv.submit(p, max_new_tokens=1).get(timeout=600)

            ev_before = _alert_events()
            tr_before = {s: _trans(s) for s in ("firing", "resolved")}

            def serve(p):
                stt, body = post(router.address, "/worker_generate",
                                 {"prompt_ids": [int(t) for t in p],
                                  "max_new_tokens": new_tokens})
                assert stt == 200, body

            # clean phase: fast traffic, rule must stay inactive
            st.sample_now(now=0.0)
            for p in prompts[:2]:
                serve(p)
            st.sample_now(now=2.0)
            st.sample_now(now=4.0)
            assert eng.firing() == [], \
                f"clean traffic fired {eng.firing()}"

            # the storm: a mid-stream dispatch kill (failover resumes
            # it) + per-step delays pushing every TTFT past the 500 ms
            # objective on both the engine and the router scope
            plan = rel.FaultPlan(seed=seed)
            plan.add("router.dispatch", "raise", times=1, after=1)
            plan.add("llm.step", "delay", times=None, delay=0.6)
            rel.set_plan(plan)
            try:
                for p in prompts:
                    serve(p)
            finally:
                rel.set_plan(None)
            fired_at = st.sample_now(now=6.0)
            assert RULE in eng.firing(), \
                "fast-burn rule not firing on the first evaluation " \
                f"after the storm: {eng.status()}"
            out["fired_at"] = fired_at
            out["events_fired"] = [f"{s}:{a}" for s, a in plan.fired]

            # live surfaces while firing (the HTTP arms default `now`
            # to wall clock, so the windows must reach back to the
            # fake-clock sample timestamps)
            stt, body = get(w1.address, "/alerts")
            assert stt == 200 and RULE in body["firing"], body
            q = quote('bigdl_slo_requests_total{slo="ttft",'
                      'verdict="violated"}', safe="")
            stt, body = get(router.address,
                            f"/metrics/query?series={q}&window=1e15"
                            "&fn=delta")
            assert stt == 200 and (body["value"] or 0) > 0, body
            stt, body = get(router.address,
                            "/fleet/timeline?series="
                            "bigdl_slo_requests_total&window=1e15")
            assert stt == 200 and body["merged"], body
            if obs.enabled():
                assert (obs.REGISTRY.sample_value("bigdl_alerts_firing")
                        or 0) >= 1, "bigdl_alerts_firing gauge not set"

            # storm deltas still inside both windows: one clean sample
            # must NOT flap the alert off (the long window's job)
            serve(prompts[0])
            st.sample_now(now=8.0)
            assert RULE in eng.firing(), \
                "alert flapped off while the storm was in-window"

            # recovery: windows drain past the storm; clean traffic
            # between the next ticks evaluates to zero burn
            st.sample_now(now=30.0)
            for p in prompts[:2]:
                serve(p)
            st.sample_now(now=32.0)
            assert eng.firing() == [], \
                f"alert did not resolve after recovery: {eng.status()}"
            rule_st = [r for r in eng.status()["rules"]
                       if r["name"] == RULE][0]
            assert rule_st["state"] == "resolved", rule_st

            # the reconciliation: transitions == flight events, EXACTLY
            ev_delta = {k: _alert_events()[k] - ev_before[k]
                        for k in ev_before}
            tr_delta = {s: _trans(s) - tr_before[s]
                        for s in ("firing", "resolved")}
            assert ev_delta == {"fire": 1, "resolve": 1}, \
                f"flight alert events off: {ev_delta}"
            if obs.enabled():
                assert tr_delta == {"firing": 1.0, "resolved": 1.0}, \
                    f"transition counters off: {tr_delta}"
                out["transitions"] = tr_delta
            out["alert_events"] = ev_delta
            out["sample_overhead_us"] = st.status()["sample_overhead_us"]
        finally:
            rel.set_plan(None)
            if not was_enabled:
                rel.disable()
            router.stop()
            w1.stop()
            w2.stop()
            s1.stop()
            s2.stop()

        # --- part 3: autoscaler decision identity -----------------------
        # same synthesized restart-free trace through (a) a live
        # FleetController reading the WindowedCounter primitive and
        # (b) a replay of the old summed max(total-last, 0) formula —
        # pressure/idle/action must be IDENTICAL tick for tick
        class _StubRouter:
            def __init__(self):
                self._pool_lock = threading.Lock()
                self.decode_workers = [("stub", 1), ("stub", 2)]

        def _sig(sheds_by, queue, active, workers):
            return {"workers": workers, "queue": queue, "active": active,
                    "inflight": 0, "sheds": sum(sheds_by.values()),
                    "sheds_by": dict(sheds_by), "occupancy_max": 0.0,
                    "queue_interactive": 0.0, "parked_by": {}}

        trace = [
            _sig({"a:1": 0.0, "b:1": 0.0}, 0.0, 1.0, 2),
            _sig({"a:1": 2.0, "b:1": 0.0}, 0.0, 1.0, 2),  # sheds grew
            _sig({"a:1": 2.0, "b:1": 3.0}, 5.0, 1.0, 2),  # grew + queue
            _sig({"a:1": 2.0, "b:1": 3.0}, 0.0, 1.0, 2),  # flat
            _sig({"a:1": 2.0}, 0.0, 0.0, 1),              # b departs flat
            _sig({"a:1": 2.0}, 0.0, 0.0, 1),              # idle, n == min
        ]
        ctl = FleetController(_StubRouter(), min_workers=1,
                              max_workers=4, sustain=2, cooldown=0.0,
                              queue_high=2.0, idle_low=0.0)
        it = iter(trace)
        ctl.signals = lambda: next(it)
        for _ in trace:
            ctl.tick()
        legacy = []
        last_sum = None
        hot = cold = 0
        for sig in trace:
            total = sum(sig["sheds_by"].values())
            delta = 0.0 if last_sum is None \
                else max(total - last_sum, 0.0)
            last_sum = total
            n = sig["workers"]
            pressure = (sig["queue"] > ctl.queue_high * max(n, 1)
                        or delta > 0
                        or (n > 0 and sig["occupancy_max"] > 0.9)
                        or (ctl.pressure_interactive
                            and sig["queue_interactive"]
                            > ctl.queue_high))
            idle = (sig["queue"] + sig["active"]
                    + sig["inflight"]) <= ctl.idle_low
            if pressure:
                hot += 1
                cold = 0
            elif idle:
                cold += 1
                hot = 0
            else:
                hot = cold = 0
            action = "none"
            if pressure and hot >= ctl.sustain and n < ctl.max_workers:
                action = "scale_out"
                hot = 0
            elif idle and cold >= ctl.sustain and n > ctl.min_workers:
                action = "scale_in"
                cold = 0
            legacy.append({"shed_delta": delta, "pressure": pressure,
                           "idle": idle, "action": action})
        got = [{k: d[k] for k in ("shed_delta", "pressure", "idle",
                                  "action")} for d in ctl.decisions]
        if got != legacy:
            raise AssertionError(
                "autoscaler diverged from the legacy shed-delta "
                f"formula on a restart-free trace:\n new={got}\n "
                f"old={legacy}")
        assert [d["action"] for d in got].count("scale_out") == 1, got
        # where the primitive intentionally differs: a member restart
        # is a reset for THAT member (its post-restart count is the
        # delta), not a clamp that swallows every other member's sheds
        wc = ts.WindowedCounter()
        assert wc.observe({"m": 10.0}) == 0.0
        assert wc.observe({"m": 14.0}) == 4.0
        assert wc.observe({"m": 3.0}) == 3.0
        out["autoscaler_decisions"] = "identical"
    finally:
        for k, v in prev.items():
            if v is None:
                conf.unset(k)
            else:
                conf.set(k, v)
        ts.reset()
        alerts.reset()
    out["match"] = True
    return out


def run_fleet_chaos(seed: int = 0, smoke: bool = False) -> dict:
    """ISSUE 15 acceptance: the elastic-fleet soak. A fleet-enabled
    router (autoscaler + graceful drain) over a
    :class:`LocalWorkerProvider` pool is driven by the closed-loop
    load generator (tools/loadgen.py) through spike → scale-out →
    worker KILLED mid-drain → scale-in cycles, with a seeded mid-stream
    ``router.dispatch`` kill and ``worker.drain`` delays widening the
    drain windows. The contract:

    - **zero lost requests** across every phase (sheds retry, failures
      fail over, drains bounce — none of it reaches the client);
    - greedy outputs **bit-identical** to ``model.generate`` goldens;
    - a gracefully drained worker's warm KV chains land on the
      survivor and serve **prefix hits** there (asserted via a chain
      only the drained worker held);
    - the pool **converges** back to ``min`` workers;
    - ``bigdl.llm.fleet.enabled=false`` is structurally absent: no
      drain coordinator, no controller thread, no ``bigdl_fleet_*``
      series, ``/worker_drain`` and ``/fleet/autoscaler`` answer 404.

    ``smoke=True`` shrinks the request counts (same phases, same
    assertions) for the bench telemetry block."""
    import threading
    import time as _time

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
    from bigdl_tpu.utils.conf import conf
    from tools.loadgen import gen_prompts, run_load

    n_requests = 6 if smoke else 8
    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    prompts = gen_prompts(n_requests, seed=seed, shared_prefix=16)
    budgets = [2 + 2 * (j % 2) for j in range(n_requests)]
    want = [list(map(int,
                     model.generate(p[None], max_new_tokens=b)
                     [0, len(p):]))
            for p, b in zip(prompts, budgets)]

    def get(addr, path):
        import http.client
        import json as _json
        conn = http.client.HTTPConnection(*addr, timeout=5)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            return r.status, _json.loads(r.read().decode())
        finally:
            conn.close()

    # --- disabled-mode structural absence (bigdl.llm.fleet.enabled
    # off, the default): no drain coordinator, endpoints 404, no
    # controller thread, no bigdl_fleet_* series
    s0 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8)
    w0 = LLMWorker(s0, role="decode").start()
    before = set(obs.render().splitlines()) if obs.enabled() else set()
    r0 = LLMRouter([], [w0.address], failover=True,
                   start_prober=False).start()
    try:
        assert w0._drain is None, "fleet-off worker built a drain"
        assert r0._fleet is None, "fleet-off router built a controller"
        st, _ = get(w0.address, "/worker_drain")
        assert st == 404, f"/worker_drain answered {st} with fleet off"
        st, _ = get(r0.address, "/fleet/autoscaler")
        assert st == 404, f"/fleet/autoscaler answered {st} fleet-off"
        if obs.enabled():
            grown = "\n".join(set(obs.render().splitlines()) - before)
            assert "bigdl_fleet_" not in grown, \
                f"fleet-off mode grew fleet series:\n{grown}"
        assert not [t for t in threading.enumerate()
                    if t.name.startswith(("bigdl-fleet",))], \
            "fleet-off mode started a fleet thread"
    finally:
        r0.stop()
        w0.stop()
        s0.stop(drain=False)

    # --- the soak
    from bigdl_tpu.llm.fleet import LocalWorkerProvider
    with conf._lock:
        prev_sync = conf._set_layer.get("bigdl.llm.kvtier.sync")
    conf.set("bigdl.llm.kvtier.sync", "true")   # inline migrations:
    was_enabled = rel.enabled()                 # deterministic spills
    if not was_enabled:
        rel.enable()
    provider = LocalWorkerProvider(
        model, server_kwargs=dict(
            max_batch=2, max_seq_len=64, page_size=8, num_pages=24,
            kvcache=True, kvtier=True, host_pages=64, max_queue=8))
    router = None
    plan = rel.FaultPlan(seed=seed)
    try:
        seed_addr = provider.launch()
        seed_srv = provider.servers()[seed_addr]
        # warm every served shape (full prefill buckets + the partial
        # suffix shapes resumes and prefix hits use); the compiled-step
        # cache is shared across engines, so scaled-out workers reuse
        # these programs
        for p, b in zip(prompts, budgets):
            seed_srv.submit(p, max_new_tokens=b).get(timeout=600)
            seed_srv.submit(p, max_new_tokens=b).get(timeout=600)
        router = LLMRouter(
            [], [seed_addr], failover=True, failover_attempts=8,
            start_prober=False, fleet=True, provider=provider,
            start_fleet=False, fleet_opts=dict(
                min_workers=1, max_workers=3, interval=0.05,
                cooldown=0.0, sustain=1, queue_high=1.0, idle_low=0.0,
                drain_timeout=20.0)).start()
        fleet = router._fleet

        def tick_until(cond, timeout):
            t0 = _time.time()
            while _time.time() - t0 < timeout:
                fleet.tick()
                if cond():
                    return True
                _time.sleep(0.02)
            return False

        def pool_size():
            with router._pool_lock:
                return len(router.decode_workers)

        # one mid-stream connection kill (the journal-resume path) +
        # per-chain drain delays (widens the mid-drain kill window)
        plan.add("router.dispatch", "raise", times=1, after=6)
        plan.add("worker.drain", "delay", times=None, delay=0.05)
        rel.set_plan(plan)

        lost = 0
        results = {}

        def load_phase(name, qps):
            out = {}

            def run():
                out["res"] = run_load(router.address, prompts,
                                      max_new_tokens=budgets, qps=qps,
                                      concurrency=4)
            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t, out

        # phase A: spike against one worker -> sustained queue
        # pressure -> scale-out; a seeded mid-stream kill fails over
        t, holder = load_phase("spike", qps=200.0)
        scaled = tick_until(lambda: pool_size() >= 2, timeout=30.0)
        t.join(timeout=600)
        res_a = holder["res"]
        results["spike"] = {k: res_a[k] for k in
                            ("sent", "ok", "lost", "retries_503")}
        lost += res_a["lost"]
        if not scaled:
            raise AssertionError(
                "fleet soak: the load spike never scaled the pool out "
                f"(signals: {fleet.signals()})")
        if res_a["outputs"] != want:
            raise AssertionError(
                f"fleet soak divergence in the spike phase: "
                f"{res_a['outputs']} vs {want}")

        # phase B: idle -> scale-in begins -> KILL the victim
        # mid-drain; the controller must remove the corpse, losing
        # nothing (its in-flight was already drained, its chains
        # re-prefill)
        if not tick_until(lambda: fleet._draining is not None,
                          timeout=30.0):
            raise AssertionError(
                "fleet soak: idle pool never began a scale-in drain")
        victim = tuple(fleet._draining["addr"])
        deadline = _time.time() + 10.0
        while _time.time() < deadline:
            try:
                _st, body = get(victim, "/worker_drain")
            except Exception:   # noqa: BLE001
                break
            if body.get("state") in ("migrating", "drained"):
                break
            _time.sleep(0.01)
        provider.kill(victim)
        if not tick_until(lambda: fleet._draining is None, timeout=30.0):
            raise AssertionError(
                "fleet soak: the controller never resolved the "
                "killed-mid-drain worker")
        if fleet.drains_lost < 1:
            raise AssertionError(
                "fleet soak: the mid-drain kill was not observed as a "
                f"lost drain (events: {fleet.events[-8:]})")

        # phase C: spike again -> scale out; plant a chain ONLY the
        # new worker holds; idle -> GRACEFUL drain must migrate it to
        # the survivor, where it serves a prefix hit
        t, holder = load_phase("respike", qps=200.0)
        scaled = tick_until(lambda: pool_size() >= 2, timeout=30.0)
        t.join(timeout=600)
        res_c = holder["res"]
        results["respike"] = {k: res_c[k] for k in
                              ("sent", "ok", "lost", "retries_503")}
        lost += res_c["lost"]
        if not scaled:
            raise AssertionError(
                "fleet soak: the second spike never scaled out")
        if res_c["outputs"] != want:
            raise AssertionError(
                f"fleet soak divergence in the respike phase: "
                f"{res_c['outputs']} vs {want}")
        with router._pool_lock:
            newbie = tuple(router.decode_workers[-1])
        if newbie == seed_addr:
            raise AssertionError("fleet soak: LIFO victim selection "
                                 "would drain the seed worker")
        rs = np.random.RandomState(seed + 1234)
        unique = rs.randint(0, 250, 24).astype(np.int32)
        new_srv = provider.servers()[newbie]
        new_srv.submit(unique, max_new_tokens=2).get(timeout=600)
        reused_before = seed_srv._kv.prefix_tokens_reused
        if not tick_until(
                lambda: fleet.scale_ins >= 1 and pool_size() == 1,
                timeout=60.0):
            raise AssertionError(
                "fleet soak: the graceful scale-in never converged "
                f"(events: {fleet.events[-8:]})")
        graceful = [e for e in fleet.events
                    if e["action"] == "scale_in"
                    and e.get("outcome") == "drained"]
        if not graceful or not any(e.get("chains", 0) > 0
                                   for e in graceful):
            raise AssertionError(
                "fleet soak: the graceful drain migrated no warm KV "
                f"chains (events: {fleet.events[-8:]})")
        # the migrated chain serves a prefix hit on the survivor
        seed_srv.submit(unique, max_new_tokens=2).get(timeout=600)
        reused_after = seed_srv._kv.prefix_tokens_reused
        if reused_after <= reused_before:
            raise AssertionError(
                "fleet soak: the survivor served no prefix hit from "
                "the drained worker's migrated chains "
                f"(reused {reused_before} -> {reused_after})")

        if not any(s == "router.dispatch" for s, _ in plan.fired):
            raise AssertionError(
                "fleet soak armed but the mid-stream router.dispatch "
                "kill never fired — widen the kill window")
        if lost:
            raise AssertionError(
                f"fleet soak lost {lost} request(s): {results}")
        # the engine ledger is back to idle on the survivor (every
        # page charge returned across all the churn)
        idle_budget = seed_srv._budget_avail
        out = {
            "seed": seed,
            "requests_per_phase": n_requests,
            "phases": results,
            "events_fired": [f"{s}:{a}" for s, a in plan.fired],
            "scale_outs": fleet.scale_outs,
            "scale_ins": fleet.scale_ins,
            "drains_lost": fleet.drains_lost,
            "chains_migrated": sum(e.get("chains", 0)
                                   for e in graceful),
            "failovers": router.failovers,
            "converged_workers": pool_size(),
            "survivor_idle_budget": idle_budget,
            "lost_requests": lost,
            "match": True,
        }
        return out
    finally:
        rel.set_plan(None)
        if not was_enabled:
            rel.disable()
        if router is not None:
            router.stop()
        provider.stop_all()
        if prev_sync is None:
            conf.unset("bigdl.llm.kvtier.sync")
        else:
            conf.set("bigdl.llm.kvtier.sync", prev_sync)


def run_preempt_chaos(seed: int = 0, smoke: bool = False) -> dict:
    """ISSUE 17 acceptance: the priority storm. Sustained batch-class
    decodes saturate every slot; an interactive burst arrives; the
    SLO-class scheduler must preempt batch victims LOSSLESSLY — with
    seeded ``llm.preempt`` faults aborting preemption attempts
    mid-decision — and every request (preempted or not) must complete
    with greedy output bit-identical to its unpreempted
    ``model.generate`` golden, zero lost. The flight-recorder
    ``preempt``/``preempt_resume`` events, the
    ``bigdl_llm_preemptions_total`` counter, and the engine's plain-int
    ledgers must reconcile EXACTLY, the KV ledger/arena must return to
    idle, and interactive TTFT must be measurably better than the same
    storm with the scheduler off (FIFO).

    Also asserts the disabled-mode contract: with
    ``bigdl.llm.priority.enabled`` off (the default) the engine builds
    no scheduler objects, mints no priority metric series, and serves
    the identical storm FIFO bit-identical — the class stamp is carried
    but inert."""
    import time as _time

    import numpy as np

    from bigdl_tpu import observability as obs
    from bigdl_tpu import reliability as rel
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.observability import flight
    from bigdl_tpu.utils.conf import conf

    GATE = "bigdl.llm.priority.enabled"
    FLIGHT_GATE = "bigdl.observability.flight.enabled"
    n_batch = 3 if smoke else 4
    n_inter = 2 if smoke else 4
    # the victim budget sets the FIFO baseline's slot-turnover time;
    # the preempted path's TTFT is independent of it, so a long batch
    # budget is what makes "measurably better" robust to CI jitter
    batch_budget = 16
    inter_budget = 3
    num_pages = 32

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, 250, 8).astype(np.int32)
    batch_prompts = [np.concatenate(
        [shared, rs.randint(0, 250, 6 + 2 * (j % 3)).astype(np.int32)])
        for j in range(n_batch)]
    inter_prompts = [rs.randint(0, 250, 6 + j % 4).astype(np.int32)
                     for j in range(n_inter)]
    prompts = batch_prompts + inter_prompts
    budgets = [batch_budget] * n_batch + [inter_budget] * n_inter
    classes = ["batch"] * n_batch + ["interactive"] * n_inter
    want = [list(map(int,
                     model.generate(p[None], max_new_tokens=b)
                     [0, len(p):]))
            for p, b in zip(prompts, budgets)]

    def storm(priority: bool):
        """One storm: saturate the 2 slots with batch decodes, then
        burst the interactive prompts. Returns (outputs-in-submit-
        order, interactive TTFTs, server) — the server already
        stopped, so its ledgers are post-drain."""
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        num_pages=num_pages, kvcache=True, kvtier=True,
                        host_pages=64, priority=priority).start()
        try:
            b_reqs = [srv.submit(p, max_new_tokens=batch_budget,
                                 priority="BATCH")     # case-insensitive
                      for p in batch_prompts]
            # the burst must land while batch decodes hold every slot —
            # wait for first tokens, not just admission
            deadline = _time.time() + 120.0
            while _time.time() < deadline and \
                    sum(1 for r in b_reqs if len(r.tokens) >= 1) < 2:
                _time.sleep(0.005)
            i_reqs = [srv.submit(p, max_new_tokens=inter_budget,
                                 priority="interactive")
                      for p in inter_prompts]
            outs = [list(map(int, r.get(timeout=600)))
                    for r in b_reqs + i_reqs]
            ttfts = [r.t_first_token - r.t_submit for r in i_reqs
                     if r.t_first_token]
        finally:
            srv.stop()
        return outs, ttfts, srv

    with conf._lock:
        prev_sync = conf._set_layer.get("bigdl.llm.kvtier.sync")
        prev_flight = conf._set_layer.get(FLIGHT_GATE)
    conf.set("bigdl.llm.kvtier.sync", "true")   # inline migrations:
    was_enabled = rel.enabled()                 # deterministic spills
    if not was_enabled:
        rel.enable()
    try:
        # --- part 1: disabled mode (the conf default) is structurally
        # absent — no scheduler objects, no priority series, and the
        # storm serves FIFO bit-identical with the class stamp inert
        lines_before = (set(obs.render().splitlines())
                        if obs.enabled() else set())
        srv0 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                         num_pages=num_pages, kvcache=True).start()
        try:
            assert srv0._sched is None and srv0._parked is None, \
                f"{GATE} off (the default) built scheduler state"
            reqs0 = [srv0.submit(p, max_new_tokens=b, priority=c)
                     for p, b, c in zip(prompts, budgets, classes)]
            outs0 = [list(map(int, r.get(timeout=600))) for r in reqs0]
            assert srv0.preemptions_total == 0 \
                and srv0.preempt_parked == 0
            assert srv0.class_depths() is None, \
                f"{GATE} off still reports class depths"
        finally:
            srv0.stop()
        if outs0 != want:
            raise AssertionError(
                f"priority-off storm is not FIFO bit-identical: "
                f"{outs0} vs {want}")
        if obs.enabled():
            grown = "\n".join(set(obs.render().splitlines())
                              - lines_before)
            for name in ("bigdl_llm_preemptions_total",
                         "bigdl_llm_queue_depth_class",
                         "bigdl_llm_preempt_parked"):
                assert name not in grown, \
                    f"{GATE} off grew metric series {name}"

        # warm the resume shapes: a second pass over every prompt hits
        # the radix chains the first pass indexed, compiling the
        # partial-prefill suffix programs preempt resumes re-enter
        # (the compiled-step cache is shared across engine instances)
        srv_w = LLMServer(model, max_batch=2, max_seq_len=64,
                          page_size=8, num_pages=num_pages,
                          kvcache=True).start()
        try:
            for p, b in zip(prompts, budgets):
                srv_w.submit(p, max_new_tokens=b).get(timeout=600)
                srv_w.submit(p, max_new_tokens=b).get(timeout=600)
        finally:
            srv_w.stop()

        # --- part 2: the FIFO reference storm (scheduler off) under
        # the same step-delay plan — the TTFT baseline the scheduler
        # must beat. llm.step delays stretch every decode pass so the
        # batch saturation genuinely blocks the burst.
        plan_off = rel.FaultPlan(seed=seed)
        plan_off.add("llm.step", "delay", times=None, delay=0.02)
        rel.set_plan(plan_off)
        try:
            outs_off, ttft_off, _ = storm(priority=False)
        finally:
            rel.set_plan(None)
        if outs_off != want:
            raise AssertionError(
                f"FIFO reference storm diverged: {outs_off} vs {want}")

        # --- part 3: the priority storm, scheduler on, flight recorder
        # on, seeded llm.preempt faults aborting preemption attempts
        # (the site fires before any state mutates, so an aborted
        # attempt must leave the victim decoding untouched and the
        # next engine pass retries the preemption)
        conf.set(FLIGHT_GATE, "true")
        r = flight.ring()
        evs = r.events() if r is not None else []
        t_before = {
            "preempt": sum(1 for e in evs if e["kind"] == "preempt"),
            "resume": sum(1 for e in evs
                          if e["kind"] == "preempt_resume"),
            "dropped": r.dropped if r is not None else 0,
        }
        c_before = _counter_total("bigdl_llm_preemptions_total")
        plan = rel.FaultPlan(seed=seed)
        plan.add("llm.preempt", "raise", times=1, after=0)
        plan.add("llm.preempt", "delay", times=None, delay=0.005)
        plan.add("llm.step", "delay", times=None, delay=0.02)
        rel.set_plan(plan)
        try:
            outs_on, ttft_on, srv = storm(priority=True)
        finally:
            rel.set_plan(None)
        if outs_on != want:
            raise AssertionError(
                f"priority storm diverged under preemption "
                f"(fired: {[f'{s}:{a}' for s, a in plan.fired]}): "
                f"{outs_on} vs {want}")
        if srv.preemptions_total == 0:
            raise AssertionError(
                "priority storm completed without a single preemption "
                "— the burst never displaced a batch decode")
        if not any(s == "llm.preempt" for s, _ in plan.fired):
            raise AssertionError(
                "priority storm armed but no llm.preempt fault fired")
        if srv.preempt_resumes_total != srv.preemptions_total:
            raise AssertionError(
                f"{srv.preemptions_total} preemptions but "
                f"{srv.preempt_resumes_total} resumes — a preempted "
                "request never re-admitted")
        # ledger/arena idle: every page charge returned at the drain,
        # every parked handoff blob consumed by its resume
        if srv._budget_avail != num_pages - 1:
            raise AssertionError(
                f"priority storm ledger leak: idle budget "
                f"{srv._budget_avail} vs pool {num_pages - 1}")
        if srv.preempt_parked != 0:
            raise AssertionError(
                f"{srv.preempt_parked} exported chains still parked "
                "after every request completed")
        if srv._tier is not None and srv._tier.migrator.inflight():
            raise AssertionError("arena migrations still in flight")
        # reconciliation: flight events == counter == plain-int ledger
        r = flight.ring()
        evs = r.events() if r is not None else []
        t_after = {
            "preempt": sum(1 for e in evs if e["kind"] == "preempt"),
            "resume": sum(1 for e in evs
                          if e["kind"] == "preempt_resume"),
            "dropped": r.dropped if r is not None else 0,
        }
        if t_after["dropped"] != t_before["dropped"]:
            raise AssertionError(
                "flight ring dropped events mid-check; raise "
                "bigdl.observability.flight.capacity")
        ev_preempt = t_after["preempt"] - t_before["preempt"]
        ev_resume = t_after["resume"] - t_before["resume"]
        if ev_preempt != srv.preemptions_total:
            raise AssertionError(
                f"{ev_preempt} flight preempt events vs "
                f"{srv.preemptions_total} ledger preemptions")
        if ev_resume != srv.preempt_resumes_total:
            raise AssertionError(
                f"{ev_resume} flight preempt_resume events vs "
                f"{srv.preempt_resumes_total} ledger resumes")
        counters_reconciled: object = "obs disabled: ledger-only"
        if c_before is not None:
            c_delta = _counter_total("bigdl_llm_preemptions_total") \
                - c_before
            if c_delta != srv.preemptions_total:
                raise AssertionError(
                    f"bigdl_llm_preemptions_total moved {c_delta} for "
                    f"{srv.preemptions_total} ledger preemptions")
            counters_reconciled = True
        # the headline: interactive TTFT measurably better than FIFO
        worst_on = max(ttft_on) if ttft_on else None
        worst_off = max(ttft_off) if ttft_off else None
        if worst_on is None or worst_off is None:
            raise AssertionError("a storm stamped no interactive TTFT")
        if worst_on >= worst_off:
            raise AssertionError(
                f"scheduler-on interactive TTFT {worst_on * 1e3:.1f}ms "
                f"is no better than FIFO {worst_off * 1e3:.1f}ms — "
                "preemption bought nothing")
        return {
            "seed": seed,
            "requests": len(prompts),
            "events_fired": [f"{s}:{a}" for s, a in plan.fired],
            "preemptions": srv.preemptions_total,
            "resumes": srv.preempt_resumes_total,
            "flight_events": {"preempt": ev_preempt,
                              "resume": ev_resume},
            "counters_reconciled": counters_reconciled,
            "idle_budget": srv._budget_avail,
            "parked": srv.preempt_parked,
            "interactive_ttft_on_ms": round(worst_on * 1e3, 3),
            "interactive_ttft_off_ms": round(worst_off * 1e3, 3),
            "lost_requests": 0,
            "match": True,
        }
    finally:
        if not was_enabled:
            rel.disable()
        if prev_flight is None:
            conf.unset(FLIGHT_GATE)
        else:
            conf.set(FLIGHT_GATE, prev_flight)
        if prev_sync is None:
            conf.unset("bigdl.llm.kvtier.sync")
        else:
            conf.set("bigdl.llm.kvtier.sync", prev_sync)


class ElasticUnsupported(RuntimeError):
    """This jax build cannot do loopback multi-process distributed
    init — the elastic pass is skipped, mirroring the graceful skip in
    tests/test_multihost.py."""


#: The elastic worker: an ordinary Engine.init + optimizer script
#: (everything elastic arrives via the launcher's env). The seeded kill
#: hard-exits 1-of-N processes mid-epoch in generation 0 only.
#:
#: Backend probe: loopback CPU jax.distributed can COORDINATE (the
#: membership/heartbeat/restart machinery is fully real) but cannot run
#: multi-process computations — in that case each process trains the
#: same LocalOptimizer trajectory on the full data, which preserves the
#: whole recovery contract (kill -> supervisor restart -> snapshot
#: resume -> bit-identical weights). On a TPU pod the probe passes and
#: the run takes the true DistriOptimizer shard_map path.
_ELASTIC_WORKER = textwrap.dedent("""
    import logging, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    logging.basicConfig(level=logging.INFO)   # resume lines -> the log

    from bigdl_tpu.utils.conf import conf
    from bigdl_tpu.utils.engine import Engine
    mesh = Engine.init()   # coordinator/nprocs/pid from the launcher env
    pid = jax.process_index()
    gen = conf.get_int("bigdl.elastic.generation", 0) or 0

    mode = "distri"
    try:   # can this backend actually COMPUTE across processes?
        from jax.sharding import NamedSharding, PartitionSpec as P
        jax.device_put(np.zeros(8, np.float32),
                       NamedSharding(mesh, P())).block_until_ready()
    except Exception as e:
        if "Multiprocess computations" not in str(e):
            raise
        mode = "local"
    print("MODE", mode, flush=True)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import (BaseOptimizer,
                                           DistriOptimizer,
                                           LocalOptimizer)
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.trigger import Trigger

    # seeded chaos: slow every elastic-guarded step so heartbeats and
    # snapshot commits interleave with real step traffic
    delay = float(os.environ.get("ELASTIC_CHAOS_STEP_DELAY", "0") or 0)
    if delay:
        from bigdl_tpu import reliability as rel
        plan = rel.FaultPlan(seed=0)
        plan.add("elastic.step", "delay", times=None, delay=delay)
        rel.set_plan(plan)

    # the kill: "pid:step" — die HARD (no cleanup, no checkpoint) once
    # past that step, generation 0 only
    die = os.environ.get("ELASTIC_CHAOS_DIE", "")
    if die:
        dpid, dstep = (int(v) for v in die.split(":"))
        orig = BaseOptimizer._after_iteration

        def lethal(self, params, states, opt_state, state):
            if pid == dpid and gen == 0 and state["neval"] > dstep:
                print("CHAOS_KILLED", state["neval"], flush=True)
                os._exit(17)
            return orig(self, params, states, opt_state, state)

        BaseOptimizer._after_iteration = lethal

    set_seed(0)    # identical init on every process (ModelBroadcast)
    model = nn.Sequential().add(nn.Linear(10, 16)).add(nn.ReLU())\\
        .add(nn.Linear(16, 2)).add(nn.LogSoftMax())

    # 4 global batches of 64 rows per epoch
    nproc = jax.process_count()
    rs = np.random.RandomState(0)
    x_all = rs.rand(256, 10).astype(np.float32)
    y_all = ((x_all.sum(1) > 5).astype(np.int32) + 1)
    from bigdl_tpu.feature.dataset import LocalDataSet
    if mode == "distri":
        # each process holds its own interleaved slice of every batch
        # (device order = process order on the data axis); unshuffled:
        # exact resume requires a deterministic per-epoch batch order
        lb = 64 // nproc
        x = x_all.reshape(4, nproc, lb, 10)[:, pid].reshape(-1, 10)
        y = y_all.reshape(4, nproc, lb)[:, pid].reshape(-1)
        opt = DistriOptimizer(model, LocalDataSet(x, y, shuffle=False),
                              nn.ClassNLLCriterion(), batch_size=lb,
                              end_trigger=Trigger.max_epoch(3))
    else:
        # replicated local training: every process runs the identical
        # trajectory over the full data
        opt = LocalOptimizer(model,
                             LocalDataSet(x_all, y_all, shuffle=False),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_epoch(3))
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_checkpoint(os.environ["ELASTIC_CHAOS_CKPT"],
                       Trigger.every_epoch())
    trained = opt.optimize()   # resume swaps opt.model: hash the result

    import hashlib
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(trained.parameters_dict()):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    print("WHASH", h.hexdigest(), flush=True)
""")


def _elastic_run(ckpt_dir: str, die: str = "", step_delay: float = 0.05,
                 timeout: float = 600.0):
    """One launcher-supervised worker-set run; returns (record,
    final-generation WHASH list, launcher)."""
    from bigdl_tpu.elastic.launch import ElasticJobFailed, ElasticLauncher

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "ELASTIC_CHAOS_CKPT": ckpt_dir,
        "ELASTIC_CHAOS_STEP_DELAY": str(step_delay),
        # fast detection for the harness; production defaults are in conf
        "BIGDL_TPU_ELASTIC_HEARTBEAT_INTERVAL": "0.1",
        "BIGDL_TPU_ELASTIC_HEARTBEAT_TIMEOUT": "5.0",
        "BIGDL_TPU_ELASTIC_SNAPSHOT_EVERY": "2",
    })
    if die:
        env["ELASTIC_CHAOS_DIE"] = die
    else:
        env.pop("ELASTIC_CHAOS_DIE", None)

    launcher = ElasticLauncher([sys.executable, "-c", _ELASTIC_WORKER],
                               nprocs=2, max_restarts=2, env=env,
                               cwd=repo_root)
    try:
        record = launcher.run(timeout=timeout)
    except ElasticJobFailed as e:
        blob = " ".join(e.log_tails.values())
        if ("DISTRIBUTED" in blob.upper() or "coordinator" in blob.lower()
                or "UNAVAILABLE" in blob):
            raise ElasticUnsupported(
                f"loopback jax.distributed unsupported: {blob[-300:]}"
            ) from e
        raise
    gen = launcher.supervisor.generation
    hashes = []
    for pid in range(launcher.nprocs):
        path = os.path.join(record["log_dir"], f"worker-g{gen}-p{pid}.log")
        with open(path, errors="replace") as f:
            lines = [ln.split()[1] for ln in f
                     if ln.startswith("WHASH")]
        hashes.append(lines[-1] if lines else None)
    with open(os.path.join(record["log_dir"], "worker-g0-p0.log"),
              errors="replace") as f:
        modes = [ln.split()[1] for ln in f if ln.startswith("MODE")]
    record["mode"] = modes[-1] if modes else "unknown"
    return record, hashes, launcher


def run_elastic_chaos(seed: int = 0, die_after: int = 9,
                      smoke: bool = False) -> dict:
    """ISSUE 10 acceptance: a 2-process DistriOptimizer run loses one
    process mid-epoch; the supervisor restarts the worker set; the job
    finishes with final weights BIT-IDENTICAL to the clean run at the
    same world size (snapshot-based resume at the exact saved
    iteration). Also asserts the disabled-mode contract: with
    ``bigdl.elastic.enabled=false`` the optimizer builds no supervisor,
    no agent thread, no snapshot ring, and mints no ``bigdl_elastic_*``
    metric series. ``smoke`` currently only shortens the wall-clock
    budget (the run is already minimal: 3 epochs x 4 tiny steps)."""
    import threading

    from bigdl_tpu import observability as obs

    # --- disabled-mode structural absence (in-process, cheap)
    before = set(obs.render().splitlines()) if obs.enabled() else set()
    clean_disabled = _train_once(32, 1, 16, ckpt_dir=None)
    assert np.isfinite(clean_disabled)
    from bigdl_tpu.optim.optimizer import BaseOptimizer  # noqa: F401
    assert not [t for t in threading.enumerate()
                if t.name.startswith("bigdl-elastic")], \
        "elastic-disabled training started an elastic thread"
    if obs.enabled():
        grown = "\n".join(set(obs.render().splitlines()) - before)
        assert "bigdl_elastic_" not in grown, \
            f"disabled mode grew elastic series:\n{grown}"

    timeout = 420.0 if smoke else 600.0
    with tempfile.TemporaryDirectory() as d_clean, \
            tempfile.TemporaryDirectory() as d_kill:
        clean_rec, clean_hashes, _ = _elastic_run(
            os.path.join(d_clean, "ckpt"), die="", timeout=timeout)
        kill_rec, kill_hashes, kill_launcher = _elastic_run(
            os.path.join(d_kill, "ckpt"), die=f"1:{die_after}",
            timeout=timeout)

        # the kill actually fired, mid-epoch, and the set restarted
        g0p1 = os.path.join(kill_rec["log_dir"], "worker-g0-p1.log")
        with open(g0p1, errors="replace") as f:
            killed = [ln for ln in f if ln.startswith("CHAOS_KILLED")]
        resumed = []
        for pid in range(2):
            path = os.path.join(kill_rec["log_dir"],
                                f"worker-g1-p{pid}.log")
            if os.path.exists(path):
                with open(path, errors="replace") as f:
                    resumed += [ln for ln in f if "auto-resuming" in ln]
    out = {
        "seed": seed,
        "die_after": die_after,
        "mode": kill_rec["mode"],
        "clean": {k: clean_rec[k] for k in ("generations", "restarts")},
        "kill": {k: kill_rec[k] for k in ("generations", "restarts")},
        "kill_failures": kill_rec["failures"],
        "clean_hashes": clean_hashes,
        "kill_hashes": kill_hashes,
        "match": (clean_hashes[0] is not None
                  and len(set(clean_hashes + kill_hashes)) == 1),
    }
    if not killed:
        raise AssertionError(
            "elastic chaos armed but process 1 never died — the kill "
            f"step {die_after} landed outside the run")
    if kill_rec["restarts"] < 1:
        raise AssertionError(
            "elastic chaos lost a process but the supervisor never "
            f"restarted the worker set: {kill_rec}")
    if not resumed:
        raise AssertionError(
            "generation 1 never auto-resumed from the snapshot tier — "
            "recovery restarted training from scratch")
    if clean_rec["restarts"] != 0:
        raise AssertionError(
            f"the clean elastic run restarted: {clean_rec}")
    if not out["match"]:
        raise AssertionError(
            f"elastic chaos divergence: clean {clean_hashes} vs "
            f"recovered {kill_hashes} — recovery replayed or dropped "
            "work")
    # a passing run does not leak worker-log dirs into /tmp across
    # repeated chaos/bench/test invocations; failures above keep them
    # for diagnostics
    import shutil
    for rec in (clean_rec, kill_rec):
        shutil.rmtree(rec["log_dir"], ignore_errors=True)
    return out


def run_all_chaos(seed: int = 0) -> dict:
    """Every chaos suite, one record per pass (the ``chaos_all``
    telemetry block in ``bench.py``). Each pass asserts its own
    parity contract; a failing pass lands as an ``error`` entry
    instead of killing the others.

    ISSUE 11: the whole run executes under the ``bigdl.analysis.
    lockwatch`` runtime witness — every lock the suites construct is
    order-checked against the process-global table, and ANY observed
    inversion fails the run (``ok: false`` + the violating pair in the
    ``lockwatch`` block). The knob is restored afterwards so the
    process leaves the way it came."""
    from bigdl_tpu.analysis import lockwatch
    from bigdl_tpu.utils.conf import conf

    # restore-exactly bookkeeping: remember whether the SET LAYER had
    # an explicit value (conf.get would return the baked-in default and
    # re-setting that would shadow the env/file layers forever), and
    # whether a caller already installed the witness (then its edge
    # table and installation are theirs — don't reset or uninstall)
    with conf._lock:
        prev = conf._set_layer.get("bigdl.analysis.lockwatch")
    was_installed = lockwatch.installed()
    conf.set("bigdl.analysis.lockwatch", "true")
    if not was_installed:
        lockwatch.reset()
    installed = lockwatch.maybe_install() or was_installed
    out = {}
    try:
        for name, fn in (("train", lambda: run_chaos(seed=seed, events=3,
                                                     smoke=True)),
                         ("kvcache", lambda: run_kvcache_chaos(seed=seed)),
                         ("kvtier", lambda: run_kvtier_chaos(seed=seed)),
                         ("mixed", lambda: run_mixed_chaos(seed=seed)),
                         ("spec", lambda: run_spec_chaos(seed=seed)),
                         ("failover", lambda: run_failover_chaos(
                             seed=seed, smoke=True)),
                         ("flight", lambda: run_flight_chaos(
                             seed=seed, smoke=True)),
                         ("fleet", lambda: run_fleet_chaos(
                             seed=seed, smoke=True)),
                         ("preempt", lambda: run_preempt_chaos(
                             seed=seed, smoke=True)),
                         ("elastic", lambda: run_elastic_chaos(
                             seed=seed, smoke=True)),
                         ("alerts", lambda: run_alerts_chaos(
                             seed=seed, smoke=True)),
                         ("api", lambda: run_api_chaos(
                             seed=seed, smoke=True))):
            try:
                out[name] = fn()
            except ElasticUnsupported as e:
                out[name] = {"skipped": repr(e)}  # no loopback distributed
            except Exception as e:  # noqa: BLE001 — one bad suite
                out[name] = {"error": repr(e)}  # must not hide the rest
    finally:
        violations = lockwatch.violations()
        out["lockwatch"] = {"installed": installed,
                            "edges_observed": len(
                                lockwatch.observed_edges()),
                            "violations": violations}
        if installed and not was_installed:
            lockwatch.uninstall()
        if prev is None:
            conf.unset("bigdl.analysis.lockwatch")
        else:
            conf.set("bigdl.analysis.lockwatch", prev)
    out["ok"] = all("error" not in v for v in out.values()
                    if isinstance(v, dict)) and not violations
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--events", type=int, default=5)
    ap.add_argument("--full", action="store_true",
                    help="bigger model/data than the smoke default")
    ap.add_argument("--kvcache", action="store_true",
                    help="run the kvcache.evict eviction-race pass "
                         "instead of the training chaos run (ISSUE 5)")
    ap.add_argument("--kvtier", action="store_true",
                    help="run the host-tier migration-fault pass: "
                         "delayed/failed spills and fetches must keep "
                         "greedy outputs identical (ISSUE 6)")
    ap.add_argument("--mixed", action="store_true",
                    help="run the chunked-admission fault pass: a "
                         "seeded llm.chunk raise mid-chain must free "
                         "the partial chain's pages/budget, fail the "
                         "request retriably, and a resubmission must "
                         "be greedy-identical to the clean run "
                         "(ISSUE 14)")
    ap.add_argument("--failover", action="store_true",
                    help="run the router kill-storm pass: mid-stream "
                         "decode-worker kills and watchdog-tripping "
                         "engine stalls must lose zero requests with "
                         "greedy outputs bit-identical (ISSUE 7)")
    ap.add_argument("--flight", action="store_true",
                    help="run the flight-recorder reconciliation pass: "
                         "a kill storm + pool-pressure replay with the "
                         "recorder on — shed/failover/eviction decision "
                         "events must reconcile EXACTLY with the "
                         "bigdl_*_total counters, and disabled mode "
                         "(bigdl.observability.flight.enabled off) "
                         "must be structurally absent (ISSUE 16)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the elastic-fleet soak: load spike -> "
                         "scale-out -> worker killed mid-drain -> "
                         "scale-in, with zero lost requests, greedy "
                         "outputs bit-identical to a clean run, and "
                         "drained workers' warm KV chains serving "
                         "prefix hits on survivors (ISSUE 15)")
    ap.add_argument("--preempt", action="store_true",
                    help="run the priority-storm pass: an interactive "
                         "burst over saturated batch-class decodes "
                         "with seeded llm.preempt faults — every "
                         "preempted request completes bit-identical, "
                         "zero lost, flight events/counters/ledgers "
                         "reconcile exactly, and interactive TTFT "
                         "beats the scheduler-off baseline (ISSUE 17)")
    ap.add_argument("--elastic", action="store_true",
                    help="run the elastic-training pass: a seeded kill "
                         "of 1-of-2 DistriOptimizer processes mid-"
                         "epoch must recover via the supervisor with "
                         "final weights bit-identical to the clean "
                         "run (ISSUE 10)")
    ap.add_argument("--spec", action="store_true",
                    help="run the self-speculative fault pass: seeded "
                         "llm.spec raises/delays mid-verify must degrade "
                         "to plain decode with greedy outputs "
                         "bit-identical to the clean run, zero page-"
                         "budget leak, and draft/verify flight events "
                         "reconciling exactly with the engine ledgers "
                         "and bigdl_llm_spec_* counters (ISSUE 19)")
    ap.add_argument("--alerts", action="store_true",
                    help="run the time-series/alerting pass: a seeded "
                         "failover storm must flip the fast-burn SLO "
                         "alert to firing within one evaluation "
                         "interval and resolve after recovery, with "
                         "transitions reconciling exactly against "
                         "flight alert_fire/alert_resolve events, the "
                         "autoscaler making identical decisions "
                         "through the store primitive, and disabled "
                         "mode structurally absent (ISSUE 18)")
    ap.add_argument("--api", action="store_true",
                    help="run the OpenAI gateway pass: a mid-stream "
                         "router.dispatch kill under a live SSE client "
                         "must keep the concatenated stream "
                         "bit-identical to model.generate with every "
                         "relayed token SLO-stamped exactly once, and "
                         "disabled mode must 404 naming "
                         "bigdl.llm.api.enabled with zero bigdl_api_* "
                         "series (ISSUE 20)")
    ap.add_argument("--all", action="store_true",
                    help="run every chaos suite (train, kvcache, "
                         "kvtier, mixed, failover, fleet, preempt, "
                         "spec, elastic, alerts, api) and report one "
                         "record per pass (the bench.py chaos_all "
                         "block)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (sitecustomize pins the "
                         "axon TPU platform; env vars are ineffective)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.all:
        out = run_all_chaos(seed=args.seed)
        print(json.dumps(out, indent=1))
        if not out["ok"]:
            sys.exit(1)
        return
    if args.api:
        out = run_api_chaos(seed=args.seed)
    elif args.spec:
        out = run_spec_chaos(seed=args.seed)
    elif args.elastic:
        out = run_elastic_chaos(seed=args.seed)
    elif args.alerts:
        out = run_alerts_chaos(seed=args.seed)
    elif args.preempt:
        out = run_preempt_chaos(seed=args.seed)
    elif args.flight:
        out = run_flight_chaos(seed=args.seed)
    elif args.fleet:
        out = run_fleet_chaos(seed=args.seed)
    elif args.mixed:
        out = run_mixed_chaos(seed=args.seed)
    elif args.failover:
        out = run_failover_chaos(seed=args.seed)
    elif args.kvtier:
        out = run_kvtier_chaos(seed=args.seed)
    elif args.kvcache:
        out = run_kvcache_chaos(seed=args.seed)
    else:
        out = run_chaos(seed=args.seed, events=args.events,
                        smoke=not args.full)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Prefix-reuse microbench (ISSUE 5 satellite): TTFT and prefill-tokens
saved on a shared-system-prompt workload, cache on vs off.

Replays the canonical serving pattern prefix caching targets — every
request is ``shared system prompt + small distinct user tail`` — against
the LIVE engine (admission, radix lookup, partial prefill, decode,
release; everything a deployment runs), once with
``bigdl.llm.kvcache.enabled`` off and once on. What it reports:

- ``ttft_ms`` per mode: mean/p50 submit→first-token wall (the always-on
  ``Request.t_submit``/``t_first_token`` stamps) — prefix reuse shows up
  here because the suffix-only prefill is a fraction of the full one;
- ``prefill_tokens`` per mode and ``prefill_tokens_saved`` (the
  engine's always-on tally): the compute the cache deleted;
- ``hits``/``evictions`` so a mis-sized pool is visible in the record.

Wired into ``bench.py``'s telemetry block (``telemetry.
microbench_prefix``) and the compact northstar line (``prefix_cache``);
``tools/bench_regress.py`` diffs the ``ttft_ms`` fields across rounds.
Standalone:

    python tools/microbench_prefix.py                 # tiny model
    python tools/microbench_prefix.py --requests 16 --shared-len 96 \
        --tail-len 8 --json
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

# runnable both as `python tools/microbench_prefix.py` and as an import
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_prefix_bench(n_requests: int = 8, shared_len: int = 48,
                     tail_len: int = 6, new_tokens: int = 4,
                     page_size: int = 16, pipeline_depth: int = 2,
                     model=None) -> Dict:
    """Serve ``n_requests`` shared-prefix prompts sequentially (the
    reuse-friendly arrival order: request N's prefill runs after the
    shared pages exist) in both modes; report TTFT and tokens saved.
    One untimed warmup request per mode absorbs the prefill/decode
    compiles — partial-prefill buckets only exist in the cache-on mode,
    so each mode warms its own path."""
    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    if model is None:
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=256)
    rs = np.random.RandomState(0)
    vocab = model.config.vocab_size
    shared = rs.randint(0, vocab, shared_len).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rs.randint(0, vocab, tail_len)
                               .astype(np.int32)])
               for _ in range(n_requests)]
    max_seq = min(shared_len + tail_len + new_tokens + 2,
                  model.config.max_position_embeddings)
    # pool big enough to keep every request's chain warm: eviction
    # thrash would make the cache-on numbers measure the wrong thing
    # (a deliberately small pool is the hammer TEST, not the bench)
    per_req = -(-(shared_len + tail_len + new_tokens) // page_size)
    num_pages = 1 + (n_requests + 2) * per_req
    out: Dict = {"requests": n_requests, "shared_len": shared_len,
                 "tail_len": tail_len, "new_tokens": new_tokens,
                 "page_size": page_size}
    for mode, key in ((False, "cache_off"), (True, "cache_on")):
        srv = LLMServer(model, max_batch=2, max_seq_len=max_seq,
                        page_size=page_size, num_pages=num_pages,
                        kvcache=mode,
                        pipeline_depth=pipeline_depth).start()
        try:
            # warmup: one untimed pass over the WHOLE workload compiles
            # every prefill bucket the timed pass will touch (cache-on
            # matched lengths stabilize once the chains exist) and
            # seeds the shared chains
            for p in prompts:
                srv.submit(p, max_new_tokens=new_tokens).get(timeout=600)
            tokens0 = srv.prefill_tokens_total
            saved0 = srv.prefix_tokens_saved
            ttfts = []
            for p in prompts:
                req = srv.submit(p, max_new_tokens=new_tokens)
                req.get(timeout=600)
                ttfts.append((req.t_first_token - req.t_submit) * 1e3)
            out[key] = {
                "ttft_ms": round(float(np.mean(ttfts)), 3),
                "ttft_p50_ms": round(float(np.median(ttfts)), 3),
                "prefill_tokens": srv.prefill_tokens_total - tokens0,
            }
            if mode:
                out[key]["hits"] = srv._kv.hits
                out[key]["evictions"] = srv._kv.evictions
                # timed-pass delta, like the sibling fields — the
                # server-lifetime tally would double-count the warmup
                out["prefill_tokens_saved"] = (srv.prefix_tokens_saved
                                               - saved0)
        finally:
            srv.stop()
    off, on = out["cache_off"], out["cache_on"]
    out["prefill_tokens_saved_vs_off"] = (off["prefill_tokens"]
                                          - on["prefill_tokens"])
    if on["ttft_ms"]:
        out["ttft_speedup"] = round(off["ttft_ms"] / on["ttft_ms"], 3)
    return out


def main(argv) -> int:
    def flag(name: str, default: Optional[str] = None):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    out = run_prefix_bench(
        n_requests=int(flag("--requests", "8")),
        shared_len=int(flag("--shared-len", "48")),
        tail_len=int(flag("--tail-len", "6")),
        new_tokens=int(flag("--new-tokens", "4")),
        page_size=int(flag("--page-size", "16")),
        pipeline_depth=int(flag("--depth", "2")))
    if "--json" in argv:
        print(json.dumps(out))
        return 0
    print(f"prefix microbench: {out['requests']} requests, shared "
          f"{out['shared_len']} + tail {out['tail_len']} tokens")
    for key in ("cache_off", "cache_on"):
        d = out[key]
        extra = (f"  hits={d['hits']} evict={d['evictions']}"
                 if "hits" in d else "")
        print(f"  {key:<10} ttft={d['ttft_ms']:>8.3f} ms  "
              f"(p50 {d['ttft_p50_ms']:.3f})  "
              f"prefill_tokens={d['prefill_tokens']}{extra}")
    print(f"  prefill tokens saved: {out.get('prefill_tokens_saved', 0)}"
          f"  ttft speedup: {out.get('ttft_speedup', 'n/a')}x")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

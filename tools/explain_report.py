#!/usr/bin/env python
"""Explain report — render a request's flight timeline + the live
roofline table (ISSUE 16 satellite).

Sources (live URL or saved JSON, mix freely):
    python tools/explain_report.py --url http://127.0.0.1:8300 \\
        --request <request_id>     # GET /debug/explain/<id> + roofline
    python tools/explain_report.py --url http://127.0.0.1:8300
        # GET /debug/flight (recent ring) + GET /metrics/snapshot
    python tools/explain_report.py explain.json      # saved explain doc
    python tools/explain_report.py snapshot.json     # saved
        # /metrics/snapshot doc: renders its "roofline" table
    python tools/explain_report.py --json ...        # machine output

The timeline prints one row per decision event (relative time, kind,
request, detail) with the one-line verdict underneath; the roofline
table prints one row per sampled jit entry point (calls, wall,
achieved TFLOP/s and GB/s, MFU / bandwidth-utilization fractions and
the memory/compute-bound verdict). Requires
``bigdl.observability.flight.enabled`` on the target process — the
endpoints 404 when the recorder is off.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.error
import urllib.request
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.telemetry_report import _print_table  # noqa: E402


def _get_json(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def fetch_explain(base_url: str, request_id: str) -> dict:
    """GET /debug/explain/<request_id> from a live worker/router."""
    return _get_json(f"{base_url.rstrip('/')}/debug/explain/{request_id}")


def fetch_flight(base_url: str, kind: Optional[str] = None,
                 limit: int = 0) -> dict:
    """GET /debug/flight (the recent ring) from a live surface."""
    url = f"{base_url.rstrip('/')}/debug/flight"
    qs = []
    if kind:
        qs.append(f"kind={kind}")
    if limit:
        qs.append(f"limit={limit}")
    if qs:
        url += "?" + "&".join(qs)
    return _get_json(url)


def fetch_roofline(base_url: str) -> Optional[dict]:
    """The "roofline" block of GET /metrics/snapshot, or None when the
    snapshot surface or the flight gate is off (best-effort: a timeline
    must render even when federation is disabled)."""
    try:
        doc = _get_json(f"{base_url.rstrip('/')}/metrics/snapshot")
    except Exception:
        return None
    return doc.get("roofline")


def timeline_rows(events: List[dict]) -> List[List]:
    """Table rows for a flight event list: relative-seconds, kind,
    request, compact detail."""
    t0 = events[0]["ts"] if events else 0.0
    rows = []
    for ev in events:
        detail = ev.get("detail", {})
        rows.append([
            f"+{ev['ts'] - t0:.3f}s", ev["kind"],
            (ev.get("request") or "")[:13],
            (ev.get("trace") or "")[:8],
            " ".join(f"{k}={v}" for k, v in sorted(detail.items()))])
    return rows


def roofline_rows(roof: dict) -> List[List]:
    return [[r["fn"], r["calls"], r["wall_s"], r["achieved_tflops"],
             r["achieved_gbps"], r.get("mfu"), r.get("bw_util"),
             r.get("bound", "-")]
            for r in roof.get("programs", [])]


def render(doc: dict, roof: Optional[dict] = None):
    """Human rendering of an explain doc, a /debug/flight doc, or a
    snapshot's roofline block (auto-detected by shape)."""
    if roof is None and "roofline" in doc:
        roof = doc["roofline"]
    events = doc.get("events")
    if events is not None:
        title = (f"flight timeline: request {doc['request']}"
                 if "request" in doc else "flight ring (recent events)")
        _print_table(title,
                     ["t", "kind", "request", "trace", "detail"],
                     timeline_rows(events))
        if "verdict" in doc:
            print(f"\nverdict: {doc['verdict']}")
        if "dropped" in doc and doc["dropped"]:
            print(f"(ring dropped {doc['dropped']} older events)")
    if roof:
        _print_table(
            f"roofline: {roof.get('device', '?')} "
            f"(peak {roof.get('peak_tflops') or '?'} TFLOP/s, "
            f"{roof.get('peak_gbps') or '?'} GB/s)",
            ["fn", "calls", "wall_s", "tflops", "gbps", "mfu",
             "bw_util", "bound"],
            roofline_rows(roof))
        if roof.get("bw_util") is not None:
            print(f"\ndevice: mfu={roof.get('mfu')} "
                  f"hbm_bw_gbps={roof.get('hbm_bw_gbps')} "
                  f"bw_util={roof.get('bw_util')}")


def main(argv: List[str]) -> int:
    as_json = "--json" in argv

    def _opt(flag: str) -> Optional[str]:
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                print(f"{flag} needs a value", file=sys.stderr)
                raise SystemExit(2)
            return argv[i + 1]
        return None

    url = _opt("--url")
    request_id = _opt("--request")
    kind = _opt("--kind")
    flags_with_val = {"--url", "--request", "--kind"}
    paths = [a for i, a in enumerate(argv) if not a.startswith("--")
             and (i == 0 or argv[i - 1] not in flags_with_val)]
    docs: List[dict] = []
    roof: Optional[dict] = None
    if url:
        try:
            if request_id:
                docs.append(fetch_explain(url, request_id))
            else:
                docs.append(fetch_flight(url, kind=kind))
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            print(f"{e.code} from {url}: {body}", file=sys.stderr)
            print("(is bigdl.observability.flight.enabled on?)",
                  file=sys.stderr)
            return 1
        roof = fetch_roofline(url)
    for p in paths:
        if not os.path.exists(p):
            print(f"no such file: {p}", file=sys.stderr)
            return 1
        with open(p) as f:
            docs.append(json.load(f))
    if not docs:
        print(__doc__)
        return 2
    for doc in docs:
        if as_json:
            out = dict(doc)
            if roof is not None and "roofline" not in out:
                out["roofline"] = roof
            print(json.dumps(out))
        else:
            render(doc, roof)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""Trace report — render one request's cross-process waterfall.

Input is Chrome-trace JSON (``observability.export_chrome_trace`` or the
``spans`` list of ``GET /debug/trace/<id>``). Spans tagged with a
``trace`` arg (the ISSUE 3 request-context machinery) group into
per-request traces; each renders as a waterfall — where the request's
wall time went: queue wait vs prefill vs decode vs postprocess — plus a
per-stage rollup.

CLI:
    python tools/trace_report.py trace.json                # slowest trace
    python tools/trace_report.py trace.json --trace <id>   # specific one
    python tools/trace_report.py trace.json --list         # all trace ids
    python tools/trace_report.py trace.json --json         # machine output
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional


def load_events(path_or_doc) -> List[dict]:
    if isinstance(path_or_doc, dict):
        doc = path_or_doc
    elif isinstance(path_or_doc, list):
        return path_or_doc
    else:
        with open(path_or_doc) as f:
            doc = json.load(f)
    if "spans" in doc and "traceEvents" not in doc:
        return doc["spans"]          # a /debug/trace/<id> body
    return doc.get("traceEvents", [])


def traces_in(events: List[dict]) -> Dict[str, List[dict]]:
    """Group complete events by their ``trace`` arg (untagged spans are
    process-local, not part of any request — skipped)."""
    out: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        trace_id = ev.get("args", {}).get("trace")
        if trace_id:
            out.setdefault(trace_id, []).append(ev)
    return out


def build_waterfall(events: List[dict], trace_id: str) -> Dict[str, Any]:
    """The per-stage timing decomposition of one trace: rows in start
    order with offsets relative to the earliest span, plus stage
    aggregates. Pure function of the span records (fake-clock
    testable)."""
    spans = sorted((e for e in events
                    if e.get("args", {}).get("trace") == trace_id),
                   key=lambda e: e.get("ts", 0.0))
    if not spans:
        return {"trace_id": trace_id, "rows": [], "stages": {},
                "wall_ms": 0.0}
    t0 = min(e["ts"] for e in spans)
    t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
    wall = max(t1 - t0, 0.0)
    rows, stages = [], {}
    for e in spans:
        args = e.get("args", {})
        stage = args.get("stage", e["name"])
        dur = e.get("dur", 0.0)
        rows.append({
            "name": e["name"], "stage": stage,
            "pid": e.get("pid"), "tid": e.get("tid"),
            "start_ms": round((e["ts"] - t0) / 1e3, 3),
            "dur_ms": round(dur / 1e3, 3),
            "frac": round(dur / wall, 4) if wall else 0.0,
        })
        stages[stage] = round(stages.get(stage, 0.0) + dur / 1e3, 3)
    return {"trace_id": trace_id, "wall_ms": round(wall / 1e3, 3),
            "span_count": len(rows), "rows": rows, "stages": stages}


def render_waterfall(wf: Dict[str, Any], width: int = 40) -> str:
    """ASCII waterfall: one bar per span, offset+length to scale."""
    lines = [f"trace {wf['trace_id']}  wall {wf['wall_ms']:.3f} ms  "
             f"{wf['span_count']} spans"]
    wall = wf["wall_ms"] or 1.0
    name_w = max((len(r["name"]) for r in wf["rows"]), default=4)
    stage_w = max((len(str(r["stage"])) for r in wf["rows"]), default=5)
    for r in wf["rows"]:
        lead = int(width * r["start_ms"] / wall)
        bar = max(int(width * r["dur_ms"] / wall), 1)
        lines.append(
            f"  {r['name']:<{name_w}}  {r['stage']:<{stage_w}}  "
            f"{' ' * lead}{'█' * bar:<{width - lead}}  "
            f"{r['dur_ms']:>9.3f} ms @ {r['start_ms']:.3f}")
    lines.append("  -- stage rollup --")
    for stage, ms in sorted(wf["stages"].items(), key=lambda kv: -kv[1]):
        pct = 100.0 * ms / wall
        lines.append(f"  {stage:<{name_w + stage_w + 2}}  "
                     f"{ms:>9.3f} ms  {pct:5.1f}%")
    return "\n".join(lines)


def report(path: str, trace_id: Optional[str] = None,
           as_json: bool = False, list_only: bool = False) -> dict:
    events = load_events(path)
    traces = traces_in(events)
    if list_only:
        summary = {tid: build_waterfall(evs, tid)
                   for tid, evs in traces.items()}
        listing = sorted(
            ({"trace_id": t, "wall_ms": w["wall_ms"],
              "spans": w["span_count"]} for t, w in summary.items()),
            key=lambda r: -r["wall_ms"])
        if as_json:
            print(json.dumps({"traces": listing}))
        else:
            for r in listing:
                print(f"{r['trace_id']}  {r['wall_ms']:>10.3f} ms  "
                      f"{r['spans']} spans")
        return {"traces": listing}
    if trace_id is None:
        if not traces:
            print("no traced spans in input", file=sys.stderr)
            return {}
        # default to the slowest request — the one worth staring at
        trace_id = max(traces, key=lambda t: build_waterfall(
            traces[t], t)["wall_ms"])
    wf = build_waterfall(traces.get(trace_id, []), trace_id)
    if as_json:
        print(json.dumps(wf))
    else:
        print(render_waterfall(wf))
    return wf


def main(argv: List[str]) -> int:
    as_json = "--json" in argv
    list_only = "--list" in argv
    trace_id = None
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 >= len(argv):
            print("--trace needs a trace id", file=sys.stderr)
            return 2
        trace_id = argv[i + 1]
    paths = [a for i, a in enumerate(argv)
             if not a.startswith("--")
             and (i == 0 or argv[i - 1] != "--trace")]
    if not paths:
        print(__doc__)
        return 2
    for p in paths:
        report(p, trace_id=trace_id, as_json=as_json,
               list_only=list_only)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

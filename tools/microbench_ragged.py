#!/usr/bin/env python
"""Ragged-prefill microbench (ISSUE 8 satellite): partial-prefill TTFT
and dense-staging volume, ragged in-place path off vs on, at several
prefix/suffix ratios.

Replays the workload the ragged kernel targets — shared cached prefix +
distinct uncached suffix, prefix cache ON — against the LIVE engine
twice per ratio: once with ``bigdl.llm.prefill.ragged`` off (the dense
gather → forward → scatter sandwich) and once on (attention reads the
prefix pages in place). What it reports, per ratio and mode:

- ``ttft_ms``: mean/p50 submit→first-token wall (``Request.t_submit`` /
  ``t_first_token``);
- ``prefill_tokens``: suffix tokens actually run through the model
  (identical across modes — the prefix cache does that saving);
- ``dense_staged_tokens``: tokens round-tripped through a dense temp
  cache (the engine's always-on ``prefill_dense_staged_tokens`` tally).
  **The ragged path must report 0** — that is the acceptance gate this
  bench exists to keep honest.

Wired into ``bench.py``'s telemetry block (``telemetry.
microbench_ragged``), the compact northstar line (``ragged_prefill``)
and ``tools/bench_regress.py`` (``ragged_{off,on}.ttft_ms`` +
``ragged.dense_staged_tokens_on``). Standalone:

    python tools/microbench_ragged.py                 # tiny model
    python tools/microbench_ragged.py --requests 8 --json
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

# runnable both as `python tools/microbench_ragged.py` and as an import
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: (shared prefix len, distinct tail len) — prefix-heavy ratios are
#: where the dense gather cost peaks and the ragged win is largest
RATIOS = ((32, 32), (48, 16), (96, 8))


def run_ragged_bench(ratios=RATIOS, n_requests: int = 6,
                     new_tokens: int = 4, page_size: int = 16,
                     pipeline_depth: int = 2, model=None) -> Dict:
    """Serve ``n_requests`` shared-prefix prompts per ratio in both
    prefill modes (prefix cache ON in both — the diff isolates the
    staging, not the reuse). One untimed warmup pass per mode absorbs
    the per-bucket prefill compiles."""
    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    if model is None:
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=256)
    rs = np.random.RandomState(0)
    vocab = model.config.vocab_size
    out: Dict = {"requests": n_requests, "new_tokens": new_tokens,
                 "page_size": page_size, "ratios": []}
    agg = {"ragged_off": [], "ragged_on": []}
    staged = {"ragged_off": 0, "ragged_on": 0}
    # ONE pool/seq size across ratios so the compiled pool shapes are
    # shared and every ratio after the first runs compile-free
    top = max(s + t for s, t in ratios)
    max_seq = min(top + new_tokens + 2,
                  model.config.max_position_embeddings)
    per_req = -(-(top + new_tokens) // page_size)
    num_pages = 1 + (n_requests + 2) * per_req
    for shared_len, tail_len in ratios:
        shared = rs.randint(0, vocab, shared_len).astype(np.int32)
        prompts = [np.concatenate([shared,
                                   rs.randint(0, vocab, tail_len)
                                   .astype(np.int32)])
                   for _ in range(n_requests)]
        entry: Dict = {"shared_len": shared_len, "tail_len": tail_len}
        for mode, key in ((False, "ragged_off"), (True, "ragged_on")):
            srv = LLMServer(model, max_batch=2, max_seq_len=max_seq,
                            page_size=page_size, num_pages=num_pages,
                            kvcache=True, ragged_prefill=mode,
                            pipeline_depth=pipeline_depth).start()
            try:
                # DOUBLE warmup: the first pass seeds the chains (and
                # the cold suffix buckets), the second sees the same
                # matched lengths the timed pass will — its buckets are
                # the timed pass's buckets, so compiles never leak into
                # the TTFT numbers
                for _ in range(2):
                    for p in prompts:
                        srv.submit(p, max_new_tokens=new_tokens).get(
                            timeout=600)
                tokens0 = srv.prefill_tokens_total
                staged0 = srv.prefill_dense_staged_tokens
                ttfts = []
                for p in prompts:
                    req = srv.submit(p, max_new_tokens=new_tokens)
                    req.get(timeout=600)
                    ttfts.append((req.t_first_token - req.t_submit)
                                 * 1e3)
                entry[key] = {
                    "ttft_ms": round(float(np.mean(ttfts)), 3),
                    "ttft_p50_ms": round(float(np.median(ttfts)), 3),
                    "prefill_tokens": (srv.prefill_tokens_total
                                       - tokens0),
                    "dense_staged_tokens": (
                        srv.prefill_dense_staged_tokens - staged0),
                }
                agg[key].append(entry[key]["ttft_ms"])
                staged[key] += entry[key]["dense_staged_tokens"]
            finally:
                srv.stop()
        out["ratios"].append(entry)
    for key in ("ragged_off", "ragged_on"):
        out[key] = {"ttft_ms": round(float(np.mean(agg[key])), 3)}
    out["dense_staged_tokens_off"] = staged["ragged_off"]
    out["dense_staged_tokens_on"] = staged["ragged_on"]
    if out["ragged_on"]["ttft_ms"]:
        out["ttft_speedup"] = round(
            out["ragged_off"]["ttft_ms"] / out["ragged_on"]["ttft_ms"],
            3)
    return out


def main(argv) -> int:
    def flag(name: str, default: Optional[str] = None):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    out = run_ragged_bench(
        n_requests=int(flag("--requests", "6")),
        new_tokens=int(flag("--new-tokens", "4")),
        page_size=int(flag("--page-size", "16")),
        pipeline_depth=int(flag("--depth", "2")))
    if "--json" in argv:
        print(json.dumps(out))
        return 0
    print(f"ragged-prefill microbench: {out['requests']} requests/ratio, "
          f"prefix cache on")
    for entry in out["ratios"]:
        print(f"  prefix {entry['shared_len']:>3} + tail "
              f"{entry['tail_len']:<3}", end="")
        for key in ("ragged_off", "ragged_on"):
            d = entry[key]
            print(f"  {key}: ttft={d['ttft_ms']:>8.3f} ms "
                  f"staged={d['dense_staged_tokens']:<5}", end="")
        print()
    print(f"  dense-staged tokens  off={out['dense_staged_tokens_off']}"
          f"  on={out['dense_staged_tokens_on']} (must be 0)"
          f"  ttft speedup: {out.get('ttft_speedup', 'n/a')}x")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

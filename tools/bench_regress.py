#!/usr/bin/env python
"""Bench regression diff — compare the latest two north-star records.

The driver appends one ``BENCH_r<N>.json`` per round whose ``tail``
field holds the JSON lines ``bench.py`` printed (the full record first,
the compact ``northstar_summary`` record last — the tail may be
truncated from the HEAD, which is exactly why the compact record is
printed last). This tool parses the newest two rounds, flattens every
numeric metric it can find, and prints per-metric deltas, warning when a
move exceeds the threshold (default 10%) — a throughput cliff between
rounds should be a red line in the log, not something a human spots by
eyeballing two JSON blobs.

CLI:
    python tools/bench_regress.py                 # ./BENCH_r*.json
    python tools/bench_regress.py --dir path --warn-pct 5 --json
    python tools/bench_regress.py --progress      # append one summary
                                                  # line to PROGRESS.jsonl

Library: ``compare_latest(dir)`` is embedded by ``bench.py`` as the
optional ``regress`` block of its output record.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time
from typing import Any, Dict, List, Optional


def _bench_files(directory: str) -> List[str]:
    files = glob.glob(os.path.join(directory, "BENCH_r*.json"))

    def round_no(path: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        return int(m.group(1)) if m else -1

    return sorted((f for f in files if round_no(f) >= 0), key=round_no)


def _json_objects(tail: str) -> List[dict]:
    """Every parseable JSON object among the tail's lines. Head
    truncation can leave the first line unparseable — skipped; a salvage
    pass then recovers the embedded ``{"metric": ...}`` sub-records
    (rounds before the compact tail record exist only in that form)."""
    out = []
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            out.append(obj)
    if not out:
        decoder = json.JSONDecoder()
        for m in re.finditer(r'\{"metric"', tail):
            try:
                obj, _ = decoder.raw_decode(tail, m.start())
            except ValueError:
                continue
            if isinstance(obj, dict):
                out.append(obj)
    return out


def _flatten_northstar(ns: dict) -> Dict[str, float]:
    flat: Dict[str, float] = {}
    for key, val in ns.items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[key] = float(val)
        elif isinstance(val, dict):
            for sub, sv in val.items():
                if sub in ("unit", "error"):
                    continue
                if isinstance(sv, (int, float)) \
                        and not isinstance(sv, bool):
                    name = key if sub == "v" else f"{key}.{sub}"
                    flat[name] = float(sv)
    return flat


# full-record metric names → the compact northstar keys, so rounds that
# predate the compact tail record (or whose compact line was truncated
# away) still diff against newer ones in one namespace
_ALIASES = {
    "resnet50_imagenet_train_throughput": "resnet_img_s",
    "bert_base_finetune_throughput": "bert",
    "llama2_7b_int4_prefill_4k": "prefill_4k",
    "lenet_convergence_top1": "lenet_top1",
    "cifar_resnet20_convergence_top1": "cifar_top1",
    "llama2_7b_int4_decode_throughput": "llama_b1",
    "llama_7b_paged_decode_step": "paged_b8",
}


def _canon(metric: str, extra: Optional[dict]) -> str:
    if metric == "llama2_7b_int4_decode_throughput" and \
            isinstance(extra, dict) and extra.get("batch") == 8:
        return "llama_b8"
    return _ALIASES.get(metric, metric)


# latency fields lifted out of each record's ``extra`` into their own
# ``<name>.<field>`` metrics: throughput can hold steady while per-step
# latency (ISSUE 4) or time-to-first-token (ISSUE 5's prefix cache)
# regresses, so the diff tracks them explicitly. (The prefix bench's
# TTFT pair rides the telemetry block, lifted separately below; this
# generic lift covers records that carry the field directly.)
_EXTRA_FIELDS = ("step_ms", "ttft_ms")


def _extra_field(extra: Optional[dict], field: str) -> Optional[float]:
    val = (extra or {}).get(field)
    return float(val) if isinstance(val, (int, float)) \
        and not isinstance(val, bool) else None


def _flatten_full(rec: dict) -> Dict[str, float]:
    """Top-level + embedded sub-record values, PLUS each record's
    ``extra.step_ms``/``extra.ttft_ms`` under ``<name>.<field>``."""
    flat: Dict[str, float] = {}
    if isinstance(rec.get("value"), (int, float)):
        name = _canon(rec.get("metric", "value"), rec.get("extra"))
        flat[name] = float(rec["value"])
        for field in _EXTRA_FIELDS:
            val = _extra_field(rec.get("extra"), field)
            if val is not None:
                flat[f"{name}.{field}"] = val
    for key, sub in (rec.get("extra") or {}).items():
        if isinstance(sub, dict) and \
                isinstance(sub.get("value"), (int, float)):
            name = _canon(sub.get("metric", key), sub.get("extra"))
            flat[name] = float(sub["value"])
            for field in _EXTRA_FIELDS:
                val = _extra_field(sub.get("extra"), field)
                if val is not None:
                    flat[f"{name}.{field}"] = val
    # ISSUE 5: the prefix microbench's TTFT pair lives in the full
    # record's telemetry block, not in a metric sub-record — lift it so
    # rounds diff TTFT even when the compact northstar line (which
    # carries the same pair as prefix_cache.ttft_*) was truncated away
    mb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("microbench_prefix") or {})
    for mode in ("cache_off", "cache_on"):
        val = _extra_field(mb.get(mode), "ttft_ms")
        if val is not None:
            flat[f"prefix_{mode}.ttft_ms"] = val
    # ISSUE 6: the tier microbench's replay pair + the savings number —
    # a tier that silently stops fetching would show up as
    # tier_tokens_saved collapsing toward zero between rounds
    tb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("microbench_tier") or {})
    for mode in ("tier_off", "tier_on"):
        val = _extra_field(tb.get(mode), "ttft_ms")
        if val is not None:
            flat[f"{mode}.ttft_ms"] = val
    for field in ("prefill_tokens_saved_vs_off", "ttft_speedup"):
        val = tb.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"tier.{field}"] = float(val)
    # ISSUE 8: the ragged-prefill pair + the dense-staging tally — a
    # regression that silently re-routes prefill through the dense temp
    # cache shows up as ragged.dense_staged_tokens_on leaving zero
    rb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("microbench_ragged") or {})
    for mode in ("ragged_off", "ragged_on"):
        val = _extra_field(rb.get(mode), "ttft_ms")
        if val is not None:
            flat[f"{mode}.ttft_ms"] = val
    for field in ("dense_staged_tokens_on", "dense_staged_tokens_off",
                  "ttft_speedup"):
        val = rb.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"ragged.{field}"] = float(val)
    # ISSUE 13: the static-analysis gate's per-pass finding counts — a
    # pass whose total creeps up between rounds means new baselined (or
    # worse, about-to-be-baselined) findings; surface the drift next to
    # the perf metrics instead of inside a JSON blob nobody diffs
    sa = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("static_analysis") or {})
    for p, n in (sa.get("by_pass") or {}).items():
        if isinstance(n, (int, float)) and not isinstance(n, bool):
            flat[f"analysis.findings.{p}"] = float(n)
    for field in ("new", "suppressed", "stale_baseline"):
        val = sa.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"analysis.{field}"] = float(val)
    # ISSUE 14: the mixed-load microbench — the decode stream's ITL
    # p99 and the long admission's TTFT, split vs unified dispatch.
    # The headline keys (mixed.itl_p99_ms / mixed.ttft_ms) carry the
    # ON mode — the number serving actually pays once the gate ships —
    # and the off/on pairs keep the delta visible round over round
    xb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("mixed_dispatch") or {})
    for mode in ("mixed_off", "mixed_on"):
        for field in ("itl_p99_ms", "ttft_ms"):
            val = _extra_field(xb.get(mode), field)
            if val is not None:
                flat[f"{mode}.{field}"] = val
    for field in ("itl_p99_ms", "ttft_ms"):
        val = _extra_field(xb.get("mixed_on"), field)
        if val is not None:
            flat[f"mixed.{field}"] = val
    # ISSUE 19: the self-speculative decode microbench — the headline
    # keys (spec.tokens_per_s / spec.accepted_per_tick / spec.speedup)
    # carry the ON mode and the on/off ratio; accept_rate drifting down
    # round over round means the proposer stopped matching (workload or
    # adaptive-k regression) even if tok/s hasn't moved yet
    sb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("spec_decode") or {})
    for mode in ("spec_off", "spec_on"):
        for field in ("tokens_per_s", "itl_p99_ms"):
            val = _extra_field(sb.get(mode), field)
            if val is not None:
                flat[f"{mode}.{field}"] = val
    val = _extra_field(sb.get("spec_on"), "tokens_per_s")
    if val is not None:
        flat["spec.tokens_per_s"] = val
    for field, key in (("accepted_tokens_per_tick", "accepted_per_tick"),
                       ("accept_rate", "accept_rate"),
                       ("tokens_per_s_ratio", "speedup")):
        val = sb.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"spec.{key}"] = float(val)
    # ISSUE 12: the fleet telemetry plane's merged sketch percentiles —
    # client-visible tail latency through the federated router. A
    # regression in p99 TTFT or inter-token latency between rounds is
    # exactly the number the serving PRs are judged on, so it diffs
    # like any throughput metric (±10% warn, same alias machinery)
    fb = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("fleet") or {})
    for field in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
                  "itl_p99_ms"):
        val = fb.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"fleet.{field}"] = float(val)
    # ISSUE 15: the elastic-fleet soak — tail latency paid WHILE the
    # pool scales, plus the robustness invariants (requests_lost must
    # pin at 0; scale-event counts drifting to 0 means the autoscaler
    # stopped reacting)
    fe = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("fleet_elastic") or {})
    for field in ("ttft_p99_ms", "itl_p99_ms", "latency_p99_ms",
                  "requests_lost", "scale_outs", "scale_ins"):
        val = fe.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"fleet_elastic.{field}"] = float(val)
    # ISSUE 17: the priority-storm chaos pass — the TTFT pair is the
    # headline (interactive latency with the scheduler on vs the FIFO
    # baseline of the SAME storm; the on-number creeping toward the
    # off-number means preemption stopped buying anything), and the
    # robustness invariants pin at their contract values (lost 0,
    # parked 0, resumes == preemptions)
    pb = ((((rec.get("extra") or {}).get("telemetry") or {})
          .get("chaos_all") or {}).get("preempt") or {})
    for field, key in (("interactive_ttft_on_ms", "ttft_on_ms"),
                       ("interactive_ttft_off_ms", "ttft_off_ms"),
                       ("preemptions", "preemptions"),
                       ("resumes", "resumes"),
                       ("lost_requests", "lost_requests"),
                       ("parked", "parked")):
        val = pb.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"priority.{key}"] = float(val)
    # ISSUE 18: the time-series plane — windowed-store sampling cost
    # over the live post-bench registry (creeping up means snapshot
    # cost or metric cardinality regressed) and the alert transitions
    # the built-in burn-rate rules saw (nonzero means the bench round
    # itself tripped an SLO page)
    ab = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("alerts") or {})
    for field, key in (("sample_overhead_us", "ts.sample_overhead_us"),
                       ("transitions", "alerts.transitions")):
        val = ab.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[key] = float(val)
    # ISSUE 20: the OpenAI gateway — client-visible streaming TTFT
    # through the SSE leg and the gateway's translation+framing
    # overhead vs the native stream on the same prompts; the mismatch
    # tally drifting off 0 means the gateway stopped being a faithful
    # view of the engine
    ob = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("openai_api") or {})
    for field in ("ttft_direct_p50_ms", "ttft_gateway_p50_ms",
                  "gateway_overhead_ms", "output_mismatches"):
        val = ob.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"api.{field}"] = float(val)
    # ISSUE 16: the live roofline gauges sampled while the serving
    # microbenches ran — MFU or achieved HBM bandwidth drifting down
    # between rounds is a dispatch-efficiency regression even when
    # raw tok/s still sits inside the noise band
    ub = (((rec.get("extra") or {}).get("telemetry") or {})
          .get("utilization") or {})
    for field in ("mfu", "hbm_bw_gbps", "bw_util"):
        val = ub.get(field)
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[f"util.{field}"] = float(val)
    return flat


def load_metrics(path: str) -> Dict[str, float]:
    """Flat {metric: value} from one BENCH_r*.json (or a raw bench.py
    output file). Prefers the compact northstar record (survives tail
    truncation); falls back to the full record's top-level values."""
    with open(path) as f:
        doc = json.load(f)
    objs = _json_objects(doc["tail"]) if isinstance(doc, dict) \
        and isinstance(doc.get("tail"), str) else \
        [doc] if isinstance(doc, dict) else []
    # union of BOTH name spaces: the full record's metric names (the
    # only form in pre-compact rounds / salvaged truncated tails) and
    # the compact northstar keys — the diff intersects whatever the two
    # rounds share
    flat: Dict[str, float] = {}
    for obj in objs:
        if "metric" in obj:
            flat.update(_flatten_full(obj))
    for obj in objs:
        ns = (obj.get("extra") or {}).get("northstar_summary")
        if isinstance(ns, dict):
            flat.update(_flatten_northstar(ns))
    return flat


def compare(base_path: str, head_path: str,
            warn_pct: float = 10.0) -> Dict[str, Any]:
    base = load_metrics(base_path)
    head = load_metrics(head_path)
    deltas: Dict[str, dict] = {}
    warned: List[str] = []
    for name in sorted(set(base) & set(head)):
        b, h = base[name], head[name]
        pct = (h - b) / abs(b) * 100.0 if b else None
        # a zero base has no percentage, but 0 -> N is never noise: a
        # pass gaining its first findings (analysis.findings.*), dense
        # staging reappearing from 0 — exactly the regressions the
        # zero-valued metrics exist to catch
        warn = (pct is not None and abs(pct) >= warn_pct) or \
            (b == 0 and h != 0)
        deltas[name] = {"base": b, "head": h,
                        "pct": round(pct, 2) if pct is not None else None,
                        "warn": warn}
        if warn:
            warned.append(name)
    return {"base": os.path.basename(base_path),
            "head": os.path.basename(head_path),
            "warn_pct": warn_pct, "deltas": deltas, "warned": warned,
            "only_base": sorted(set(base) - set(head)),
            "only_head": sorted(set(head) - set(base))}


def compare_latest(directory: str = ".", warn_pct: float = 10.0,
                   progress_path: Optional[str] = None
                   ) -> Optional[Dict[str, Any]]:
    """Diff the newest two rounds; None when fewer than two exist. When
    ``progress_path`` is given, one compact summary line is appended
    there (the PROGRESS.jsonl breadcrumb the ISSUE asks for)."""
    files = _bench_files(directory)
    if len(files) < 2:
        return None
    out = compare(files[-2], files[-1], warn_pct)
    if progress_path:
        line = {"ts": time.time(), "kind": "bench_regress",
                "base": out["base"], "head": out["head"],
                "metrics": len(out["deltas"]),
                "warned": out["warned"]}
        try:
            with open(progress_path, "a") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            pass   # a read-only checkout must not fail the bench
    return out


def _print(out: Dict[str, Any]):
    print(f"bench regress: {out['base']} -> {out['head']} "
          f"(warn at ±{out['warn_pct']:g}%)")
    if not out["deltas"]:
        print("  no shared metrics")
        return
    name_w = max(len(n) for n in out["deltas"])
    for name, d in out["deltas"].items():
        pct = f"{d['pct']:+.1f}%" if d["pct"] is not None else "n/a"
        flag = "  << WARN" if d["warn"] else ""
        print(f"  {name:<{name_w}}  {d['base']:>12.4g} -> "
              f"{d['head']:>12.4g}  {pct:>8}{flag}")
    for name in out["only_head"]:
        print(f"  {name:<{name_w}}  (new in {out['head']})")
    for name in out["only_base"]:
        print(f"  {name:<{name_w}}  (gone since {out['base']})")


def _flag_value(argv: List[str], flag: str) -> Optional[str]:
    if flag not in argv:
        return None
    i = argv.index(flag)
    if i + 1 >= len(argv):
        print(f"{flag} needs a value", file=sys.stderr)
        raise SystemExit(2)
    return argv[i + 1]


def main(argv: List[str]) -> int:
    directory = _flag_value(argv, "--dir") or "."
    warn = _flag_value(argv, "--warn-pct")
    warn_pct = float(warn) if warn is not None else 10.0
    progress = os.path.join(directory, "PROGRESS.jsonl") \
        if "--progress" in argv else None
    out = compare_latest(directory, warn_pct, progress_path=progress)
    if out is None:
        print("need at least two BENCH_r*.json rounds to diff",
              file=sys.stderr)
        return 1
    if "--json" in argv:
        print(json.dumps(out))
    else:
        _print(out)
    return 2 if out["warned"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Machine-counted component inventory (VERDICT r3 weak #5: STATUS.md
numbers must be reproducible, not estimated).

Counts by AST, no imports: layer classes in nn/layers/*, containers,
criterions, keras layer classes, optim methods, TFNet ops.

Usage: python tools/count_inventory.py [--list <category>]
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.join(os.path.dirname(__file__), os.pardir, "bigdl_tpu")


def _classes(path, exclude_private=True):
    out = []
    for fname in sorted(os.listdir(path)):
        if not fname.endswith(".py"):
            continue
        with open(os.path.join(path, fname)) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                if exclude_private and node.name.startswith("_"):
                    continue
                out.append((fname, node.name))
    return out


def _classes_file(path):
    with open(path) as f:
        tree = ast.parse(f.read())
    return [("", n.name) for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef) and not n.name.startswith("_")]


def _tfnet_ops():
    path = os.path.join(ROOT, "nn", "ops", "tfnet.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    ops = {"Const", "Placeholder"}        # handled inline, not in the dict
    for node in ast.walk(tree):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(getattr(t, "id", "") == "_HANDLERS" for t in targets):
            if node.value is not None and isinstance(node.value, ast.Dict):
                ops.update(k.value for k in node.value.keys
                           if isinstance(k, ast.Constant))
    return sorted(ops)


def counts():
    layer_classes = _classes(os.path.join(ROOT, "nn", "layers"))
    # Module base/mixins aren't zoo rows
    skip = {"Module", "TensorModule", "Criterion"}
    layer_classes = [c for c in layer_classes if c[1] not in skip]
    containers = _classes_file(os.path.join(ROOT, "nn", "containers.py"))
    crits = [c for c in _classes_file(os.path.join(ROOT, "nn",
                                                   "criterion.py"))
             if c[1] not in skip]
    keras = _classes_file(os.path.join(ROOT, "keras", "layers.py"))
    optim = [c for c in _classes_file(os.path.join(ROOT, "optim",
                                                   "optim_method.py"))]
    return {
        "nn_layer_classes": layer_classes,
        "nn_containers": containers,
        "criterions": crits,
        "keras_layers": keras,
        "optim_methods": optim,
        "tfnet_ops": [("", o) for o in _tfnet_ops()],
    }


if __name__ == "__main__":
    c = counts()
    if "--list" in sys.argv:
        cat = sys.argv[sys.argv.index("--list") + 1]
        for fname, name in c[cat]:
            print(f"{fname:30s} {name}")
    else:
        for k, v in c.items():
            print(f"{k}: {len(v)}")

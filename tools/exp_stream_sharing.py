"""Chip experiment (VERDICT r5 item 5): is the b1 in-context penalty
caused by DISTINCT consecutive kernels failing to share the
double-buffered weight stream?

Method: slope-time (a) a loop of the qkv-shaped matvec alone, (b) a loop
of the gate_up-shaped matvec alone, (c) a loop alternating the two, and
(d) a loop chaining all four per-layer decode matvecs (qkv -> o ->
gate_up -> down) with data dependencies, like the live layer but without
rmsnorm/rope/attention. If (c) ≈ (a)+(b) and (d) ≈ sum of all four,
kernel-transition stream sharing is NOT the bottleneck and a fused
megakernel cannot recover the gap; the residual must come from the
non-matmul ops. Uses the fori-loop slope harness (>=500 iteration
pairs) per the tenancy-noise rule."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.ggml.quantize import QK
from bigdl_tpu.llm.models.llama import _linear

H, QKV_N, INTER = 4096, 4096 + 4096 + 4096, 11008


def mk_q4(key, k, n):
    k1, k2 = jax.random.split(key)
    return {"q": jax.random.randint(k1, (k // 2, n), 0, 256, jnp.uint8),
            "scale": jax.random.uniform(k2, (k // QK, n), jnp.float32,
                                        0.001, 0.02)}


def slope(fn, iters=500):
    """Per-iteration time as the slope between iters/4 and iters."""
    def loop_for(n_it):
        @jax.jit
        def loop(x):
            def body(i, carry):
                x, acc = carry
                y = fn(x)
                return (x + y * jnp.asarray(1e-30, x.dtype), acc + y)
            return jax.lax.fori_loop(0, n_it, body, (x, jnp.float32(0)))
        return loop
    xs = [jnp.ones((1, H), jnp.bfloat16) * (1 + 1e-3 * i)
          for i in range(8)]
    xs = jax.block_until_ready(xs)
    pts, xi = [], 0
    for n_it in (iters // 4, iters):
        loop = loop_for(n_it)
        float(loop(xs[0])[1])
        best = 1e9
        for _ in range(3):
            xi += 1
            t0 = time.perf_counter()
            float(loop(xs[xi % len(xs)])[1])
            best = min(best, time.perf_counter() - t0)
        pts.append((n_it, best))
    (a1, b1), (a2, b2) = pts
    sl = (b2 - b1) / (a2 - a1)
    return sl if sl > 0 else b2 / a2


def main():
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    qkv = mk_q4(ks[0], H, QKV_N)
    o = mk_q4(ks[1], H, H)
    gate_up = mk_q4(ks[2], H, 2 * INTER)
    down = mk_q4(ks[3], INTER, H)

    t_qkv = slope(lambda x: _linear(qkv, x).sum())
    t_gu = slope(lambda x: _linear(gate_up, x).sum())
    t_o = slope(lambda x: _linear(o, x).sum())
    t_down = slope(lambda x: _linear(
        down, jnp.broadcast_to(x[:, :1], (1, INTER)).astype(x.dtype)
        * jnp.float32(1e-6).astype(x.dtype)).sum())

    def alt(x):
        return _linear(qkv, x).sum() + _linear(gate_up, x).sum()
    t_alt = slope(alt)

    def chain(x):
        y = _linear(qkv, x)                       # (1, 12288)
        a = y[:, :H] * jnp.float32(1e-6).astype(y.dtype)
        z = _linear(o, a)
        h2 = x + z
        gu = _linear(gate_up, h2)
        act = (gu[:, :INTER] * gu[:, INTER:]).astype(x.dtype)
        return _linear(down, act).sum()
    t_chain = slope(chain)

    print({
        "qkv_us": round(t_qkv * 1e6, 1),
        "gate_up_us": round(t_gu * 1e6, 1),
        "o_us": round(t_o * 1e6, 1),
        "down_us": round(t_down * 1e6, 1),
        "alt_us": round(t_alt * 1e6, 1),
        "alt_vs_sum": round(t_alt / (t_qkv + t_gu), 3),
        "chain_us": round(t_chain * 1e6, 1),
        "chain_vs_sum": round(
            t_chain / (t_qkv + t_gu + t_o + t_down), 3),
        "sum4_us": round((t_qkv + t_gu + t_o + t_down) * 1e6, 1),
    })


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Mixed-load microbench (ISSUE 14 satellite): inter-token latency of a
steady decode stream while a LONG prompt is admitted mid-run, unified
dispatch off vs on.

The TTFT-vs-ITL tradeoff this PR deletes: with the split engine, a long
admission prefills the WHOLE prompt in one pass, so every in-flight
decode stalls for that pass — the stream's p99 inter-token gap spikes
to the full prefill wall. With ``bigdl.llm.mixed.enabled`` the prompt
is fed in ``bigdl.llm.prefill.chunk_tokens`` page-aligned chunks fused
into the decode passes, so the worst gap is bounded by one chunk.

What it reports, per mode (``mixed_off`` / ``mixed_on``):

- ``itl_p50/p95/p99_ms``: percentiles of the STREAM requests' token
  gaps, computed from the engine's per-token drain stamps
  (``Request.t_tokens``, recorded by the SLO account — the exact
  fence-arrival clocks ``bigdl_llm_itl_seconds`` observes) through a
  PR 12 :class:`~bigdl_tpu.observability.sketch.QuantileSketch`;
- ``ttft_ms``: the long prompt's submit→first-token wall — chunking
  trades a bounded TTFT increase for the deleted ITL spike;
- ``chunks`` / ``mixed_passes``: the engine's always-on tallies (the
  on-mode run must actually have chunked).

Wired into ``bench.py``'s telemetry block (``telemetry.mixed_dispatch``),
the compact northstar line and ``tools/bench_regress.py``
(``mixed.itl_p99_ms`` / ``mixed.ttft_ms`` + the off/on pairs).
Standalone::

    python tools/microbench_mixed.py                    # small sizes
    python tools/microbench_mixed.py --prompt-len 2048 --json
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

# runnable both as `python tools/microbench_mixed.py` and as an import
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _pcts(sketch) -> Dict[str, float]:
    out = {}
    for q, key in ((0.5, "itl_p50_ms"), (0.95, "itl_p95_ms"),
                   (0.99, "itl_p99_ms")):
        v = sketch.quantile(q)
        out[key] = round(v * 1e3, 3) if v is not None else None
    return out


def run_mixed_bench(batch: int = 4, stream_tokens: int = 40,
                    prompt_len: int = 256, chunk_tokens: int = 32,
                    page_size: int = 16, pipeline_depth: int = 2,
                    model=None) -> Dict:
    """Decode ``batch`` steady streams; once every stream has produced
    a few tokens, admit ONE ``prompt_len``-token prompt (the 2–4k-token
    case scaled to the model at hand) and keep streaming. Both modes
    run the ragged in-place prefill (chunking requires it; forcing it
    in the off mode isolates the DISPATCH change, not the PR 8 kernel)
    and a warmup round absorbs every compile."""
    import time

    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.observability.sketch import QuantileSketch

    if model is None:
        cfg0 = LlamaConfig.tiny()
        if cfg0.max_position_embeddings < prompt_len + 24:
            # the 2–4k-token standalone case: widen the tiny config's
            # position range so the admission is genuinely long
            import dataclasses
            cfg0 = dataclasses.replace(
                cfg0, max_position_embeddings=prompt_len + 24)
        model = LlamaForCausalLM.from_config(
            cfg0, seed=0, max_cache_len=prompt_len + 64)
    cfg = model.config
    prompt_len = min(prompt_len, cfg.max_position_embeddings - 16)
    rs = np.random.RandomState(0)
    stream_prompts = [rs.randint(0, cfg.vocab_size, 8).astype(np.int32)
                      for _ in range(batch)]
    long_prompt = rs.randint(0, cfg.vocab_size,
                             prompt_len).astype(np.int32)
    max_seq = min(prompt_len + 24, cfg.max_position_embeddings)
    per_stream = -(-(8 + stream_tokens + 4) // page_size)
    num_pages = (1 + batch * per_stream
                 + -(-(prompt_len + 24) // page_size) + 4)
    out: Dict = {"batch": batch, "stream_tokens": stream_tokens,
                 "prompt_len": int(prompt_len),
                 "chunk_tokens": chunk_tokens, "page_size": page_size}
    for mode, mkey in ((False, "mixed_off"), (True, "mixed_on")):
        srv = LLMServer(model, max_batch=batch + 1, max_seq_len=max_seq,
                        page_size=page_size, num_pages=num_pages,
                        pipeline_depth=pipeline_depth,
                        ragged_prefill=True, slo=True, mixed=mode,
                        chunk_tokens=chunk_tokens).start()
        try:
            # warmup: stream + long-prompt buckets (and, mode on, the
            # mixed/chunk programs) all compile outside the timed run
            warm = [srv.submit(p, max_new_tokens=4)
                    for p in stream_prompts]
            warm.append(srv.submit(long_prompt, max_new_tokens=2))
            for r in warm:
                r.get(timeout=1200)
            chunks0 = srv.prefill_chunks_total
            streams = [srv.submit(p, max_new_tokens=stream_tokens)
                       for p in stream_prompts]
            # admit the long prompt once every stream is decoding; a
            # failed stream (done with error, tokens frozen) or a
            # wedged engine must fail the bench, not hang it — bench.py
            # only catches exceptions
            deadline = time.perf_counter() + 600
            while not all(len(r.tokens) >= 2 or r.done.is_set()
                          for r in streams):
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        "mixed bench: streams never started decoding")
                time.sleep(0.001)
            big = srv.submit(long_prompt, max_new_tokens=4)
            big.get(timeout=1200)
            for r in streams:
                r.get(timeout=1200)
            sk = QuantileSketch()
            for r in streams:
                for a, b in zip(r.t_tokens, r.t_tokens[1:]):
                    sk.observe(b - a)
            entry = _pcts(sk)
            entry["ttft_ms"] = round(
                (big.t_first_token - big.t_submit) * 1e3, 3)
            entry["itl_samples"] = sk.count
            entry["chunks"] = srv.prefill_chunks_total - chunks0
            entry["mixed_passes"] = srv.mixed_passes
            out[mkey] = entry
        finally:
            srv.stop()
    if out["mixed_on"]["chunks"] == 0:
        out["warning"] = ("unified mode never chunked — prompt_len vs "
                          "chunk_tokens leaves nothing to interleave")
    p99_off = out["mixed_off"].get("itl_p99_ms")
    p99_on = out["mixed_on"].get("itl_p99_ms")
    if p99_off and p99_on:
        out["itl_p99_ratio_off_on"] = round(p99_off / p99_on, 3)
    return out


def main(argv) -> int:
    def flag(name: str, default: Optional[str] = None):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    out = run_mixed_bench(
        batch=int(flag("--batch", "4")),
        stream_tokens=int(flag("--stream-tokens", "40")),
        prompt_len=int(flag("--prompt-len", "256")),
        chunk_tokens=int(flag("--chunk-tokens", "32")),
        page_size=int(flag("--page-size", "16")),
        pipeline_depth=int(flag("--depth", "2")))
    if "--json" in argv:
        print(json.dumps(out))
        return 0
    print(f"mixed-load microbench: {out['batch']} streams + one "
          f"{out['prompt_len']}-token admission "
          f"(chunk={out['chunk_tokens']})")
    for mkey in ("mixed_off", "mixed_on"):
        d = out[mkey]
        print(f"  {mkey:<9} itl p50={d['itl_p50_ms']} "
              f"p95={d['itl_p95_ms']} p99={d['itl_p99_ms']} ms  "
              f"ttft={d['ttft_ms']} ms  chunks={d['chunks']}")
    if "itl_p99_ratio_off_on" in out:
        print(f"  itl p99 off/on: {out['itl_p99_ratio_off_on']}x")
    if "warning" in out:
        print(f"  WARNING: {out['warning']}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

#!/usr/bin/env python
"""Per-step decode latency microbench + pipeline-depth sweep (ISSUE 4).

Drives the LIVE continuous-batching engine (LLMServer — admission,
prefill, paged pool, drain bookkeeping, everything) rather than a bare
compiled step, so what it measures is exactly what a serving deployment
pays per token: device compute PLUS whatever host work the pipeline
fails to hide. Sweeping ``bigdl.llm.pipeline_depth`` makes the async
engine's win legible as the depth-1 → depth-N step-time drop, and the
``host_ms``/``stall_ms`` split (the server's always-on accounting, the
same numbers the ``bigdl_llm_decode_host_seconds`` /
``..._stall_seconds`` histograms carry) shows WHERE the remaining time
goes — a step that is all stall is device-bound; one with host ≈ stall
is scheduling-bound and wants more depth.

Wired into ``bench.py``'s telemetry block like ``tools/chaos_check.py``
(one compact dict under ``telemetry.microbench_decode``; the northstar
summary carries the per-depth step_ms), and runnable standalone:

    python tools/microbench_decode.py                # tiny model sweep
    python tools/microbench_decode.py --depths 1,2,4 --batch 8 \
        --tokens 64 --json
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Iterable, Optional

# runnable both as `python tools/microbench_decode.py` (only the script
# dir is on sys.path then, the package root is not) and as an import
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_microbench(depths: Iterable[int] = (1, 2, 4), batch: int = 4,
                   tokens: int = 32, prompt_len: int = 8,
                   model_size: str = "tiny", paged: bool = True,
                   page_size: int = 16, warmup_tokens: int = 4,
                   model=None) -> Dict:
    """Decode ``batch`` concurrent requests of ``tokens`` new tokens each
    at every pipeline depth; report per-step wall latency and the
    host/stall attribution. The first (warmup) round per server absorbs
    prefill/decode compiles so the timed window measures steady state —
    compiled paged steps are also shared process-wide, so depths after
    the first reuse the same executables."""
    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    if model is None:
        cfg = {"tiny": LlamaConfig.tiny,
               "7b": LlamaConfig.llama2_7b}[model_size]()
        model = LlamaForCausalLM.from_config(cfg, seed=0,
                                             max_cache_len=256)
    rs = np.random.RandomState(0)
    vocab = model.config.vocab_size
    max_seq = min(prompt_len + tokens + warmup_tokens + 2,
                  model.config.max_position_embeddings)
    prompts = [rs.randint(0, vocab, prompt_len).astype(np.int32)
               for _ in range(batch)]
    out: Dict = {"batch": batch, "tokens": tokens,
                 "prompt_len": prompt_len, "paged": paged,
                 "model": model_size}
    from bigdl_tpu.observability.sketch import QuantileSketch
    for depth in depths:
        # slo=True makes the engine stamp every token's drain-fence
        # arrival on the request handle (Request.t_tokens) — the exact
        # gaps the bigdl_llm_itl_seconds sketch would observe, read
        # here without touching the global registry
        srv = LLMServer(model, max_batch=batch, max_seq_len=max_seq,
                        paged=paged, page_size=page_size,
                        pipeline_depth=depth, slo=True).start()
        try:
            # warmup: compile prefill buckets + the decode step
            for r in [srv.submit(p, max_new_tokens=warmup_tokens)
                      for p in prompts]:
                r.get(timeout=600)
            steps0, host0, stall0 = (srv.steps, srv.host_seconds,
                                     srv.stall_seconds)
            t0 = time.perf_counter()
            reqs = [srv.submit(p, max_new_tokens=tokens)
                    for p in prompts]
            got = [r.get(timeout=600) for r in reqs]
            wall = time.perf_counter() - t0
            steps = srv.steps - steps0
            # per-request inter-token gaps (ISSUE 14 satellite): the
            # tail is the number mixed-dispatch work is judged on —
            # a mean step_ms hides exactly the spikes that matter
            sk = QuantileSketch()
            for r in reqs:
                for a, b in zip(r.t_tokens, r.t_tokens[1:]):
                    sk.observe(b - a)
            p50, p99 = sk.quantile(0.5), sk.quantile(0.99)
            out[f"depth{depth}"] = {
                "step_ms": round(wall / max(steps, 1) * 1e3, 3),
                "steps": steps,
                "wall_s": round(wall, 3),
                "tokens_per_s": round(sum(len(g) for g in got) / wall, 2),
                "host_ms_per_step": round(
                    (srv.host_seconds - host0) / max(steps, 1) * 1e3, 3),
                "stall_ms_per_step": round(
                    (srv.stall_seconds - stall0) / max(steps, 1) * 1e3,
                    3),
                "itl_p50_ms": (round(p50 * 1e3, 3)
                               if p50 is not None else None),
                "itl_p99_ms": (round(p99 * 1e3, 3)
                               if p99 is not None else None),
            }
        finally:
            srv.stop()
    # best PIPELINED depth vs the synchronous engine — only meaningful
    # (and only emitted) when depth 1 was actually swept; a sweep where
    # every depth is slower than 1 reports < 1.0, not a fake speedup
    base = out.get("depth1", {}).get("step_ms")
    rest = [d["step_ms"] for k, d in out.items()
            if k.startswith("depth") and k != "depth1"]
    if base and rest:
        out["speedup_vs_depth1"] = round(base / min(rest), 3)
    return out


def run_spec_bench(tokens: int = 48, spec_k: int = 8,
                   page_size: int = 8, model=None) -> Dict:
    """Self-speculative decoding on/off sweep (ISSUE 19): batch-1
    greedy decode of a repetitive-suffix workload — the prompt repeats
    a short pattern, so the n-gram proposer's match rate is high and
    the bandwidth win is visible even on the CPU proxy. Reports raw
    tok/s both ways, the accepted-tokens-per-tick the ROADMAP bar is
    stated in (``spec_emitted_total / spec_passes``: how many tokens
    one fence delivered on average), the lifetime draft acceptance
    rate, and the on/off ITL p99. Keyed into bench_regress as
    ``spec.*`` / ``spec_{off,on}.*``."""
    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.observability.sketch import QuantileSketch

    if model is None:
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=256)
    # seed chosen so the tiny model's greedy continuation itself falls
    # into a short cycle: the proposer drafts from generated history,
    # so what must repeat is the OUTPUT, not just the prompt
    rs = np.random.RandomState(42)
    pattern = rs.randint(0, model.config.vocab_size, 5).astype(np.int32)
    prompt = np.tile(pattern, 6).astype(np.int32)    # 30 repetitive toks
    max_seq = min(len(prompt) + tokens + 8,
                  model.config.max_position_embeddings)
    out: Dict = {"tokens": tokens, "prompt_len": int(len(prompt)),
                 "spec_k": spec_k}
    got = {}
    for mode, sp in (("spec_off", False), ("spec_on", True)):
        srv = LLMServer(model, max_batch=1, max_seq_len=max_seq,
                        page_size=page_size, ragged_prefill=True,
                        pipeline_depth=1, slo=True, spec=sp,
                        spec_k=spec_k).start()
        try:
            # full-length warmup: the run is deterministic, so the
            # second pass replays the exact bucket/shape sequence —
            # every spec verify program compiles here, the timed
            # window below is steady state (and the compile-recorder
            # test pins the replay at zero new programs)
            srv.submit(prompt, max_new_tokens=tokens).get(timeout=600)
            t0 = time.perf_counter()
            req = srv.submit(prompt, max_new_tokens=tokens)
            got[mode] = list(map(int, req.get(timeout=600)))
            wall = time.perf_counter() - t0
            sk = QuantileSketch()
            for a, b in zip(req.t_tokens, req.t_tokens[1:]):
                sk.observe(b - a)
            p99 = sk.quantile(0.99)
            out[mode] = {
                "tokens_per_s": round(len(got[mode]) / wall, 2),
                "wall_s": round(wall, 3),
                "itl_p99_ms": (round(p99 * 1e3, 3)
                               if p99 is not None else None),
            }
            if sp:
                out["accepted_tokens_per_tick"] = round(
                    srv.spec_emitted_total / max(srv.spec_passes, 1), 3)
                out["accept_rate"] = round(
                    srv.spec_accepted_total
                    / max(srv.spec_proposed_total, 1), 3)
                out["spec_passes"] = srv.spec_passes
        finally:
            srv.stop()
    # the hard bar: same tokens either way (greedy bit-parity), fewer
    # ticks with speculation
    out["bit_identical"] = got["spec_off"] == got["spec_on"]
    out["tokens_per_s_ratio"] = round(
        out["spec_on"]["tokens_per_s"]
        / max(out["spec_off"]["tokens_per_s"], 1e-9), 3)
    return out


def main(argv) -> int:
    def flag(name: str, default: Optional[str] = None):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    if "--spec" in argv:
        out = run_spec_bench(tokens=int(flag("--tokens", "48")),
                             spec_k=int(flag("--spec-k", "8")))
        if "--json" in argv:
            print(json.dumps(out))
            return 0
        print(f"spec decode microbench: tokens={out['tokens']} "
              f"k={out['spec_k']} bit_identical={out['bit_identical']}")
        for mode in ("spec_off", "spec_on"):
            d = out[mode]
            print(f"  {mode:<9} {d['tokens_per_s']:>8.1f} tok/s  "
                  f"itl_p99={d['itl_p99_ms']} ms")
        print(f"  accepted/tick={out['accepted_tokens_per_tick']} "
              f"accept_rate={out['accept_rate']} "
              f"speedup={out['tokens_per_s_ratio']}x")
        return 0
    depths = tuple(int(d) for d in
                   flag("--depths", "1,2,4").split(","))
    out = run_microbench(
        depths=depths,
        batch=int(flag("--batch", "4")),
        tokens=int(flag("--tokens", "32")),
        prompt_len=int(flag("--prompt-len", "8")),
        model_size=flag("--model", "tiny"),
        paged="--slotted" not in argv)
    if "--json" in argv:
        print(json.dumps(out))
        return 0
    print(f"decode microbench: batch={out['batch']} "
          f"tokens={out['tokens']} paged={out['paged']}")
    for k in sorted(k for k in out if k.startswith("depth")):
        d = out[k]
        print(f"  {k:<7} step={d['step_ms']:>8.3f} ms  "
              f"host={d['host_ms_per_step']:>7.3f} ms  "
              f"stall={d['stall_ms_per_step']:>7.3f} ms  "
              f"itl_p99={d['itl_p99_ms']} ms  "
              f"({d['tokens_per_s']:.1f} tok/s)")
    if "speedup_vs_depth1" in out:
        print(f"  speedup vs depth {min(depths)}: "
              f"{out['speedup_vs_depth1']}x")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""Closed-loop load generator for the serving stack (ISSUE 15).

Drives mixed prompt/output-length traffic at a controlled arrival rate
through the full router → prefill → decode path (``POST
/worker_generate`` on any router or worker address) and reports what a
client actually saw: per-request completion-latency percentiles (its
own per-run :class:`~bigdl_tpu.observability.sketch.QuantileSketch` —
independent of the process-global registry), 503-shed retries, and the
number the fleet soak is judged on — **requests lost** (a request is
lost only when it exhausts its retries or fails non-retriably; a shed
that later succeeds is latency, not loss).

The generator is closed-loop with scheduled arrivals: request *i* is
due at ``t0 + i/qps``; a bounded pool of client threads picks up due
requests (falling behind under overload instead of stacking unbounded
connections — the closed-loop part), and each 503 backs off by the
server's own ``Retry-After`` (capped) before retrying.

Outputs are collected **per prompt index**, so callers can assert greedy
bit-parity against a clean run — ``tools/chaos_check.py --fleet`` does
exactly that while killing workers mid-drain.

Router-scope TTFT/ITL under soak (``bigdl_router_ttft_seconds`` /
``bigdl_router_itl_seconds`` sketches, ``bigdl.slo.enabled``) are
cumulative in the process registry; :func:`sketch_window` subtracts a
before-snapshot from an after-snapshot bucket-wise (sketch buckets are
plain counts, so the difference is itself a valid sketch of exactly the
in-between samples) — that is how ``bench.py``'s ``fleet_elastic``
block reports honest per-soak p99s from a shared registry.

Usage:
    python tools/loadgen.py --url 127.0.0.1:8000 --requests 64 \
        --qps 20 [--max-new 8] [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: mixed prompt-length ladder (tokens) the seeded generator cycles
#: through — short chat turns to page-spanning contexts
PROMPT_LENS = (6, 10, 16, 24, 40)
#: mixed output budgets paired with them
OUTPUT_LENS = (2, 4, 6, 8)

#: SLO-class request header + known classes (ISSUE 17) — kept literal
#: here so the CLI works without importing the serving stack
PRIORITY_HEADER = "X-BigDL-Priority"
PRIORITY_CLASSES = ("interactive", "standard", "batch")

#: model id the OpenAI gateway serves (ISSUE 20) — the worker/router
#: default; --openai-model overrides for renamed deployments
OPENAI_MODEL = "bigdl-tpu-llm"


def parse_priority_mix(spec: str) -> List[Tuple[str, int]]:
    """``"interactive:1,standard:1,batch:2"`` → ``[(class, weight)]``.
    Weights are relative request counts in the deterministic
    round-robin pattern :func:`assign_classes` cycles through."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        weight = int(w) if w else 1
        if weight < 0:
            raise ValueError(f"negative weight in --priority-mix: {part}")
        cls = name.strip().lower()
        if cls not in PRIORITY_CLASSES:
            # the server degrades unknown classes to "standard", but a
            # typo'd mix spec should fail fast, not skew the soak
            raise ValueError(f"unknown class in --priority-mix: {part} "
                             f"(known: {', '.join(PRIORITY_CLASSES)})")
        out.append((cls, weight))
    if not out or all(w == 0 for _, w in out):
        raise ValueError(f"empty --priority-mix spec: {spec!r}")
    return out


def assign_classes(n: int, mix: List[Tuple[str, int]]) -> List[str]:
    """Deterministic per-request class list: the weighted pattern
    (each class repeated ``weight`` times) cycled over ``n`` requests,
    so reruns of a seeded soak see identical class placement."""
    pattern = [cls for cls, w in mix for _ in range(w)]
    return [pattern[i % len(pattern)] for i in range(n)]


def gen_prompts(n: int, seed: int = 0, vocab: int = 250,
                shared_prefix: int = 0) -> List[Any]:
    """``n`` seeded int32 prompts over the length ladder; an optional
    shared prefix makes the workload prefix-cache-friendly (the drain
    migration's warm chains come from exactly such sharing)."""
    import numpy as np
    rs = np.random.RandomState(seed)
    prefix = rs.randint(0, vocab, shared_prefix).astype(np.int32) \
        if shared_prefix else None
    out = []
    for j in range(n):
        body = rs.randint(0, vocab,
                          PROMPT_LENS[j % len(PROMPT_LENS)]) \
            .astype(np.int32)
        out.append(body if prefix is None
                   else np.concatenate([prefix, body]))
    return out


def _post(addr: Tuple[str, int], body: dict, timeout: float,
          headers: Optional[dict] = None):
    import http.client
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", "/worker_generate", json.dumps(body), hdrs)
        resp = conn.getresponse()
        data = resp.read()
        try:
            parsed = json.loads(data.decode())
        except ValueError:
            parsed = {"error": data.decode(errors="replace")[:200]}
        return resp.status, parsed, resp.msg
    finally:
        conn.close()


def _post_stream(addr: Tuple[str, int], body: dict, timeout: float,
                 headers: Optional[dict] = None):
    """``/worker_generate_stream`` client leg: returns ``(status,
    final_payload, msg, ttft_s, itl_gaps_s)``. TTFT is request-send to
    the first token-bearing chunk; ITL gaps are wall time between
    consecutive token-bearing chunks (a chunk may batch tokens, so this
    is the client-visible gap, the same thing a streaming UI stalls
    on)."""
    import http.client
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        t_send = time.perf_counter()
        conn.request("POST", "/worker_generate_stream",
                     json.dumps(body), hdrs)
        resp = conn.getresponse()
        if resp.status != 200:
            data = resp.read()
            try:
                parsed = json.loads(data.decode())
            except ValueError:
                parsed = {"error": data.decode(errors="replace")[:200]}
            return resp.status, parsed, resp.msg, None, []
        ttft = None
        gaps: List[float] = []
        t_prev = None
        seen = 0
        last: dict = {}
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line.decode())
            except ValueError:
                continue
            now = time.perf_counter()
            ntok = len(obj.get("output_ids", []))
            if ntok > seen:
                if ttft is None:
                    ttft = now - t_send
                elif t_prev is not None:
                    gaps.append(now - t_prev)
                t_prev = now
                seen = ntok
            last = obj
            if obj.get("done"):
                break
        return 200, last, resp.msg, ttft, gaps
    finally:
        conn.close()


def _openai_error(parsed: dict) -> dict:
    """Normalize an OpenAI error body to the native ``{"error": msg}``
    shape the retry/report loop already understands."""
    err = parsed.get("error")
    if isinstance(err, dict):
        return {"error": err.get("message", "")}
    return parsed


def _post_openai(addr: Tuple[str, int], body: dict, timeout: float,
                 headers: Optional[dict] = None,
                 model: str = OPENAI_MODEL):
    """Blocking ``/v1/completions`` leg (ISSUE 20): same return shape
    as :func:`_post` — the choice's ``token_ids`` renamed to
    ``output_ids`` so parity asserts are endpoint-agnostic."""
    import http.client
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        req = {"model": model,
               "prompt": body["prompt_ids"],
               "max_tokens": body["max_new_tokens"]}
        conn.request("POST", "/v1/completions", json.dumps(req), hdrs)
        resp = conn.getresponse()
        data = resp.read()
        try:
            parsed = json.loads(data.decode())
        except ValueError:
            parsed = {"error": data.decode(errors="replace")[:200]}
        if resp.status != 200:
            return resp.status, _openai_error(parsed), resp.msg
        choice = parsed["choices"][0]
        return 200, {"output_ids": choice.get("token_ids", []),
                     "finish_reason": choice.get("finish_reason")}, \
            resp.msg
    finally:
        conn.close()


def _post_stream_openai(addr: Tuple[str, int], body: dict,
                        timeout: float,
                        headers: Optional[dict] = None,
                        model: str = OPENAI_MODEL):
    """SSE ``/v1/completions`` leg (ISSUE 20): same return shape as
    :func:`_post_stream`. TTFT/ITL are measured at the SSE boundary —
    the client-visible numbers the gateway's journal stamps must
    reconcile with. A mid-stream SSE ``error`` event surfaces as a
    retriable ``{"error": ...}`` final payload, mirroring the native
    stream's terminal error chunk."""
    import http.client

    from bigdl_tpu.llm.api.sse import parse_sse
    conn = http.client.HTTPConnection(addr[0], addr[1], timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        req = {"model": model,
               "prompt": body["prompt_ids"],
               "max_tokens": body["max_new_tokens"],
               "stream": True}
        t_send = time.perf_counter()
        conn.request("POST", "/v1/completions", json.dumps(req), hdrs)
        resp = conn.getresponse()
        if resp.status != 200:
            data = resp.read()
            try:
                parsed = json.loads(data.decode())
            except ValueError:
                parsed = {"error": data.decode(errors="replace")[:200]}
            return resp.status, _openai_error(parsed), resp.msg, None, []
        ttft = None
        gaps: List[float] = []
        t_prev = None
        tokens: List[int] = []
        finish = None
        err = None
        for obj in parse_sse(resp):
            now = time.perf_counter()
            if "error" in obj:
                err = _openai_error(obj)["error"]
                continue
            choice = (obj.get("choices") or [{}])[0]
            new = choice.get("token_ids", [])
            if new:
                if ttft is None:
                    ttft = now - t_send
                elif t_prev is not None:
                    gaps.append(now - t_prev)
                t_prev = now
                tokens.extend(int(t) for t in new)
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
        if err is not None:
            return 200, {"error": err}, resp.msg, ttft, gaps
        return 200, {"output_ids": tokens, "finish_reason": finish}, \
            resp.msg, ttft, gaps
    finally:
        conn.close()


def run_load(addr: Tuple[str, int], prompts: Sequence[Any],
             max_new_tokens: Any = 4, qps: float = 20.0,
             concurrency: int = 4,
             max_retries: int = 20, retry_cap_s: float = 0.25,
             request_timeout: float = 120.0,
             priorities: Optional[Sequence[str]] = None,
             stream: bool = False,
             openai: bool = False,
             openai_model: str = OPENAI_MODEL) -> Dict[str, Any]:
    """Drive ``prompts`` through ``addr`` at ``qps`` scheduled arrivals.
    ``max_new_tokens`` may be one int or a per-prompt sequence of the
    same length (the mixed-output part of the soak). ``priorities``
    (per-prompt SLO-class names, ISSUE 17) are sent as the
    ``X-BigDL-Priority`` header and split every counter/sketch per
    class under the ``per_class`` result key. ``stream=True`` uses the
    streaming endpoint so the per-class sketches include client-visible
    TTFT and ITL, not just completion latency. Returns the result
    record described in the module docstring; ``outputs[i]`` is request
    ``i``'s token list (None when lost — the zero-lost assertion is
    ``lost == 0``). ``openai=True`` (ISSUE 20) drives the same traffic
    through the gateway's ``/v1/completions`` instead — SSE when
    ``stream`` — retrying the gateway's 429 translation of a shed
    exactly like the native 503 (same Retry-After honor), so every
    parity/loss assertion is endpoint-agnostic."""
    from bigdl_tpu.observability.sketch import QuantileSketch
    n = len(prompts)
    if isinstance(max_new_tokens, (list, tuple)):
        if len(max_new_tokens) != n:
            raise ValueError(
                f"max_new_tokens has {len(max_new_tokens)} entries "
                f"for {n} prompts")
        budgets = [int(v) for v in max_new_tokens]
    else:
        budgets = [int(max_new_tokens)] * n
    if priorities is not None and len(priorities) != n:
        raise ValueError(
            f"priorities has {len(priorities)} entries for {n} prompts")
    outputs: List[Optional[List[int]]] = [None] * n
    errors: List[dict] = []
    sketch = QuantileSketch()
    lock = threading.Lock()
    counters = {"ok": 0, "lost": 0, "retries_503": 0}
    per_class: Dict[str, Dict[str, Any]] = {}
    if priorities is not None:
        for cls in priorities:
            per_class.setdefault(cls, {
                "sent": 0, "ok": 0, "lost": 0, "retries_503": 0,
                "latency": QuantileSketch(), "ttft": QuantileSketch(),
                "itl": QuantileSketch()})
            per_class[cls]["sent"] += 1
    next_idx = [0]
    t0 = time.perf_counter()

    def take() -> Optional[int]:
        with lock:
            if next_idx[0] >= n:
                return None
            i = next_idx[0]
            next_idx[0] += 1
        due = t0 + i / max(qps, 1e-9)
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        return i

    def client():
        while True:
            i = take()
            if i is None:
                return
            body = {"prompt_ids": [int(t) for t in prompts[i]],
                    "max_new_tokens": budgets[i]}
            cls = priorities[i] if priorities is not None else None
            req_headers = {PRIORITY_HEADER: cls} if cls else None
            t_req = time.perf_counter()
            last_err = "retries exhausted"
            done = False
            for _attempt in range(max_retries + 1):
                ttft = None
                gaps: List[float] = []
                try:
                    if stream and openai:
                        status, parsed, hdrs, ttft, gaps = \
                            _post_stream_openai(addr, body,
                                                request_timeout,
                                                req_headers,
                                                model=openai_model)
                    elif stream:
                        status, parsed, hdrs, ttft, gaps = \
                            _post_stream(addr, body, request_timeout,
                                         req_headers)
                    elif openai:
                        status, parsed, hdrs = _post_openai(
                            addr, body, request_timeout, req_headers,
                            model=openai_model)
                    else:
                        status, parsed, hdrs = _post(
                            addr, body, request_timeout, req_headers)
                except Exception as e:  # noqa: BLE001 — retriable
                    last_err = f"transport: {e}"
                    time.sleep(min(0.05, retry_cap_s))
                    continue
                if status == 200 and parsed.get("error") is not None:
                    # terminal stream chunk carried the engine's error
                    # (retriable) — same treatment as a transport fault
                    last_err = f"stream: {parsed['error']}"
                    time.sleep(min(0.05, retry_cap_s))
                    continue
                if status == 200:
                    lat = time.perf_counter() - t_req
                    with lock:
                        outputs[i] = [int(t)
                                      for t in parsed["output_ids"]]
                        counters["ok"] += 1
                        sketch.observe(lat)
                        if cls is not None:
                            rec = per_class[cls]
                            rec["ok"] += 1
                            rec["latency"].observe(lat)
                            if ttft is not None:
                                rec["ttft"].observe(ttft)
                            for g in gaps:
                                rec["itl"].observe(g)
                    done = True
                    break
                if status in (503, 429):
                    # backpressure: honor the server's Retry-After
                    # (capped — the soak must finish), then retry. 429
                    # is the gateway's OpenAI translation of the same
                    # shed. Shed-then-served is latency, never loss.
                    with lock:
                        counters["retries_503"] += 1
                        if cls is not None:
                            per_class[cls]["retries_503"] += 1
                    try:
                        ra = float(hdrs.get("Retry-After") or 0.05)
                    except (TypeError, ValueError):
                        ra = 0.05
                    time.sleep(min(max(ra, 0.01), retry_cap_s))
                    last_err = f"503: {parsed.get('error', '')}"
                    continue
                last_err = f"{status}: {parsed.get('error', '')}"
                break
            if not done:
                with lock:
                    counters["lost"] += 1
                    if cls is not None:
                        per_class[cls]["lost"] += 1
                    errors.append({"request": i, "error": last_err})

    threads = [threading.Thread(target=client,
                                name=f"bigdl-loadgen-{k}", daemon=True)
               for k in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    qs = sketch.quantiles((0.5, 0.95, 0.99))
    out = {
        "sent": n,
        "ok": counters["ok"],
        "lost": counters["lost"],
        "retries_503": counters["retries_503"],
        "wall_s": round(wall, 3),
        "achieved_qps": round(counters["ok"] / max(wall, 1e-9), 2),
        "latency_p50_ms": _ms(qs.get(0.5)),
        "latency_p95_ms": _ms(qs.get(0.95)),
        "latency_p99_ms": _ms(qs.get(0.99)),
        "outputs": outputs,
        "errors": errors[:16],
    }
    if priorities is not None:
        out["per_class"] = {
            cls: _class_report(rec) for cls, rec in per_class.items()}
    return out


def _class_report(rec: Dict[str, Any]) -> Dict[str, Any]:
    lat = rec["latency"].quantiles((0.5, 0.99))
    ttft = rec["ttft"].quantiles((0.5, 0.99))
    itl = rec["itl"].quantiles((0.99,))
    return {
        "sent": rec["sent"], "ok": rec["ok"], "lost": rec["lost"],
        "retries_503": rec["retries_503"],
        "latency_p50_ms": _ms(lat.get(0.5)),
        "latency_p99_ms": _ms(lat.get(0.99)),
        "ttft_p50_ms": _ms(ttft.get(0.5)),
        "ttft_p99_ms": _ms(ttft.get(0.99)),
        "itl_p99_ms": _ms(itl.get(0.99)),
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


# ---------------------------------------------------------------------------
# registry-sketch windows (per-soak TTFT/ITL out of a shared registry)
# ---------------------------------------------------------------------------

def registry_sketch_snapshot(name: str) -> Optional[dict]:
    """The unlabeled series' sketch snapshot for metric ``name`` from
    the process registry (None when absent — e.g. SLO off)."""
    from bigdl_tpu import observability as obs
    from bigdl_tpu.observability.metrics import _SketchChild
    for m in obs.REGISTRY.collect():
        if m.name != name:
            continue
        for _key, child in m.children():
            if isinstance(child, _SketchChild):
                return child.to_snapshot()
    return None


def sketch_window(before: Optional[dict], after: Optional[dict],
                  qs=(0.5, 0.95, 0.99)) -> Dict[float, Optional[float]]:
    """Quantiles of the samples observed BETWEEN two snapshots of one
    cumulative sketch. The subtraction itself moved into the
    time-series plane (ISSUE 18) — this is the shared, tested
    implementation; the thin alias here keeps the loadgen call sites
    and their importers unchanged."""
    from bigdl_tpu.observability.timeseries import (
        sketch_window as _sketch_window)
    return _sketch_window(before, after, qs)


def run_fleet_soak(n_requests: int = 8, qps: float = 100.0,
                   seed: int = 0,
                   priority_mix: Optional[str] = None,
                   openai: bool = False) -> Dict[str, Any]:
    """The ``fleet_elastic`` bench telemetry block (ISSUE 15): a
    fault-free soak of the elastic fleet — spike against one worker,
    autoscaler scale-out, graceful drain-and-scale-in back to the
    floor — reporting client-visible p99 TTFT / engine p99 ITL for
    exactly this soak's requests (SLO sketch windows), requests lost
    (must be 0), and the scale-event counts. ``priority_mix`` (an
    ISSUE 17 ``parse_priority_mix`` spec) turns on the SLO-class
    scheduler in the pool's workers, stamps each request with its
    class, and adds a ``per_class`` block — the mixed-class version of
    the same soak. ``openai=True`` (ISSUE 20) enables the gateway on
    every pool worker and the router and drives the identical soak
    through ``/v1/completions`` SSE instead of the native endpoint —
    elastic scale-out/drain must be invisible at the OpenAI boundary
    too. The chaos variant with kills lives in
    ``tools/chaos_check.py --fleet``."""
    import time as _time

    from bigdl_tpu.llm.fleet import LocalWorkerProvider
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.worker import LLMRouter
    from bigdl_tpu.utils.conf import conf

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    prompts = gen_prompts(n_requests, seed=seed, shared_prefix=16)
    classes = (assign_classes(n_requests, parse_priority_mix(
        priority_mix)) if priority_mix else None)
    with conf._lock:
        prev_sync = conf._set_layer.get("bigdl.llm.kvtier.sync")
    conf.set("bigdl.llm.kvtier.sync", "true")
    server_kwargs = dict(
        max_batch=2, max_seq_len=64, page_size=8, num_pages=24,
        kvcache=True, kvtier=True, host_pages=64, max_queue=8,
        slo=True)
    if classes is not None:
        server_kwargs["priority"] = True
    worker_kwargs = dict(api=True) if openai else None
    provider = LocalWorkerProvider(model, server_kwargs=server_kwargs,
                                   worker_kwargs=worker_kwargs)
    router = None
    ttft_before = registry_sketch_snapshot("bigdl_router_ttft_seconds")
    itl_before = registry_sketch_snapshot("bigdl_llm_itl_seconds")
    try:
        seed_addr = provider.launch()
        srv = provider.servers()[seed_addr]
        for p in prompts:       # warm the shared compiled-step cache
            srv.submit(p, max_new_tokens=1).get(timeout=600)
            srv.submit(p, max_new_tokens=1).get(timeout=600)
        router = LLMRouter(
            [], [seed_addr], failover=True, failover_attempts=8,
            start_prober=False, slo=True, fleet=True,
            provider=provider, start_fleet=False, api=openai,
            fleet_opts=dict(min_workers=1, max_workers=3,
                            interval=0.05, cooldown=0.0, sustain=1,
                            queue_high=1.0, idle_low=0.0,
                            drain_timeout=20.0)).start()
        fleet = router._fleet
        import threading as _threading
        holder: Dict[str, Any] = {}

        def _run():
            holder["res"] = run_load(router.address, prompts,
                                     max_new_tokens=4, qps=qps,
                                     concurrency=4,
                                     priorities=classes,
                                     openai=openai, stream=openai)
        t = _threading.Thread(target=_run, daemon=True)
        t.start()
        deadline = _time.time() + 60.0
        while _time.time() < deadline:
            fleet.tick()
            if not t.is_alive() and fleet.scale_ins >= 1 and \
                    len(router.decode_workers) == 1:
                break
            _time.sleep(0.02)
        t.join(timeout=600)
        res = holder.get("res") or {}
        ttft = sketch_window(
            ttft_before,
            registry_sketch_snapshot("bigdl_router_ttft_seconds"))
        itl = sketch_window(
            itl_before,
            registry_sketch_snapshot("bigdl_llm_itl_seconds"))
        out = {
            "requests": n_requests,
            "qps_target": qps,
            "requests_lost": int(res.get("lost", 0)),
            "retries_503": int(res.get("retries_503", 0)),
            "scale_outs": fleet.scale_outs,
            "scale_ins": fleet.scale_ins,
            "converged_workers": len(router.decode_workers),
            "latency_p99_ms": res.get("latency_p99_ms"),
            "ttft_p50_ms": _ms(ttft.get(0.5)),
            "ttft_p99_ms": _ms(ttft.get(0.99)),
            "itl_p99_ms": _ms(itl.get(0.99)),
        }
        if "per_class" in res:
            out["per_class"] = res["per_class"]
        return out
    finally:
        if router is not None:
            router.stop()
        provider.stop_all()
        if prev_sync is None:
            conf.unset("bigdl.llm.kvtier.sync")
        else:
            conf.set("bigdl.llm.kvtier.sync", prev_sync)


def run_openai_bench(n_requests: int = 6, max_new: int = 6,
                     seed: int = 0) -> Dict[str, Any]:
    """The ``openai_api`` bench telemetry block (ISSUE 20): one
    api-enabled worker, the same seeded prompts streamed twice — native
    ``/worker_generate_stream`` vs gateway ``/v1/completions`` SSE —
    reporting client-visible TTFT p50 for both and the gateway's added
    latency (translation + SSE framing over the same journal-free
    engine path). Outputs must be bit-identical between the two
    endpoints; mismatches are reported, not asserted (bench telemetry
    is advisory — the hard assert lives in tests/test_api.py)."""
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.worker import LLMWorker
    from bigdl_tpu.observability.sketch import QuantileSketch

    model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                         max_cache_len=128)
    prompts = gen_prompts(n_requests, seed=seed)
    srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                    kvcache=True).start()
    worker = LLMWorker(srv, api=True).start()
    try:
        for p in prompts:       # warm every compiled shape first
            srv.submit(p, max_new_tokens=1).get(timeout=600)
        addr = worker.address
        direct = QuantileSketch()
        gateway = QuantileSketch()
        mismatches = 0
        for i, p in enumerate(prompts):
            body = {"prompt_ids": [int(t) for t in p],
                    "max_new_tokens": max_new}
            st, native, _, t_direct, _ = _post_stream(
                addr, body, 120.0)
            st2, via, _, t_gw, _ = _post_stream_openai(
                addr, body, 120.0)
            if st == 200 and t_direct is not None:
                direct.observe(t_direct)
            if st2 == 200 and t_gw is not None:
                gateway.observe(t_gw)
            if st != 200 or st2 != 200 or \
                    list(native.get("output_ids", [])) != \
                    list(via.get("output_ids", [])):
                mismatches += 1
        d50 = direct.quantiles((0.5,)).get(0.5)
        g50 = gateway.quantiles((0.5,)).get(0.5)
        return {
            "requests": n_requests,
            "ttft_direct_p50_ms": _ms(d50),
            "ttft_gateway_p50_ms": _ms(g50),
            "gateway_overhead_ms": (
                None if d50 is None or g50 is None
                else round((g50 - d50) * 1000.0, 3)),
            "output_mismatches": mismatches,
        }
    finally:
        worker.stop()
        srv.stop()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", required=True,
                    help="router or worker address, host:port")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--qps", type=float, default=20.0)
    ap.add_argument("--max-new", type=int, default=4)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="tokens of seeded shared prefix across all "
                         "prompts (exercises the prefix cache)")
    ap.add_argument("--priority-mix", default=None,
                    help="mixed-class soak (ISSUE 17): weighted SLO "
                         "classes, e.g. 'interactive:1,standard:1,"
                         "batch:2' — stamps X-BigDL-Priority and "
                         "reports per-class TTFT/ITL sketches")
    ap.add_argument("--no-stream", action="store_true",
                    help="with --priority-mix or --openai, use the "
                         "blocking endpoint (per-class TTFT/ITL "
                         "unavailable; needed when a priority-mix "
                         "target is a router)")
    ap.add_argument("--openai", action="store_true",
                    help="drive the OpenAI gateway (/v1/completions, "
                         "SSE unless --no-stream) instead of the "
                         "native endpoints; requires "
                         "bigdl.llm.api.enabled on the target")
    ap.add_argument("--openai-model", default=OPENAI_MODEL,
                    help="model id to send with --openai (must match "
                         "the target's served model)")
    args = ap.parse_args()
    host, port = args.url.rsplit(":", 1)
    prompts = gen_prompts(args.requests, seed=args.seed,
                          shared_prefix=args.shared_prefix)
    classes = (assign_classes(args.requests, parse_priority_mix(
        args.priority_mix)) if args.priority_mix else None)
    out = run_load((host, int(port)), prompts,
                   max_new_tokens=args.max_new, qps=args.qps,
                   concurrency=args.concurrency,
                   priorities=classes,
                   openai=args.openai,
                   openai_model=args.openai_model,
                   stream=bool((classes is not None or args.openai)
                               and not args.no_stream))
    out.pop("outputs")          # token lists are for parity asserts,
    print(json.dumps(out, indent=1))   # not for the CLI report
    if out["lost"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Chip experiment: where do the ~9.5 ms between the paged decode step
(54.2 ms, b8 ctx256) and the fused-scan dense-cache step (~44.7 ms) go?

Times three variants of the b8/7B decode step under the same fori-loop
slope harness as bench_paged_decode_step:
  full     — the real serving step (paged_attention_stats + merge + scatter)
  noattn   — attention replaced by v (same matmuls/norms, no paged kernel)
  nomerge  — kernel runs, merge replaced by acc (no combine math)
full-noattn isolates the paged kernel + merge; full-nomerge isolates the
combine. If the kernel dominates, its (b, hkv, nblk)-grid 4 KB page DMAs
are the suspect (per-(page, head) copies are DMA-latency-bound)."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.kernels.paged_attention import (
    LANE, merge_attention_partial, paged_attention_stats)
from bigdl_tpu.llm.models.llama import (LlamaConfig, _linear,
                                        attention_qkv, mlp, rms_norm,
                                        rope_cfg)

import bench as _bench


def build_step(cfg, bt, page, num_pages, mode: str):
    def step(params, k_pages, v_pages, lens, toks):
        b = toks.shape[0]
        L = cfg.num_hidden_layers
        kp_flat = k_pages.reshape((L * num_pages,) + k_pages.shape[2:])
        vp_flat = v_pages.reshape((L * num_pages,) + v_pages.shape[2:])
        x = params["embed_tokens"][toks][:, None]
        positions = lens[:, None].astype(jnp.int32)

        def layer_step(carry, inputs):
            x, = carry
            lp, l = inputs
            h = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
            q, k, v = attention_qkv(lp, h, cfg)
            q = rope_cfg(q, positions, cfg)
            k = rope_cfg(k, positions, cfg)
            if mode == "noattn":
                attn = jnp.repeat(
                    v[:, 0], cfg.num_attention_heads
                    // cfg.num_key_value_heads, 1).astype(x.dtype)
            else:
                acc, m, lsum = paged_attention_stats(
                    q[:, 0], kp_flat, vp_flat, bt + l * num_pages, lens,
                    page_size=page)
                if mode == "nomerge":
                    attn = (acc / 256.0).astype(x.dtype)
                else:
                    attn = merge_attention_partial(
                        acc, m, lsum, q[:, 0], k[:, 0],
                        v[:, 0]).astype(x.dtype)
            x = x + _linear(lp["o_proj"], attn.reshape(b, 1, -1))
            h2 = rms_norm(x, lp["post_attention_layernorm"],
                          cfg.rms_norm_eps)
            x = x + mlp(lp, h2, x.dtype)
            return (x,), (k[:, 0], v[:, 0])

        (x,), (k_new, v_new) = jax.lax.scan(
            layer_step, (x,), (params["layers"],
                               jnp.arange(cfg.num_hidden_layers)))
        x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
        logits = _linear(params["lm_head"], x)
        pidx = lens // page
        slot = lens % page
        phys = bt[jnp.arange(b), pidx]
        k_pages = k_pages.at[:, phys, :, slot].set(
            k_new.transpose(1, 0, 2, 3).astype(k_pages.dtype))
        v_pages = v_pages.at[:, phys, :, slot].set(
            v_new.transpose(1, 0, 2, 3).astype(v_pages.dtype))
        return (logits[:, 0].astype(jnp.float32), k_pages, v_pages)

    return step


def main(batch=8, ctx_len=256, page_size=16):
    cfg = LlamaConfig.llama2_7b()
    params = _bench._synthetic_q4_llama_params(cfg)
    ppb = LANE // page_size
    cap = -(-(ctx_len + 160) // page_size)
    pages_cap = -(-cap // ppb) * ppb
    num_pages = 1 + batch * pages_cap
    nl, hkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                   cfg.head_dim)
    kk, kv = jax.random.split(jax.random.PRNGKey(1))
    shape = (nl, num_pages, hkv, page_size, hd)
    k_pages = jax.random.normal(kk, shape, jnp.bfloat16) * 0.1
    v_pages = jax.random.normal(kv, shape, jnp.bfloat16) * 0.1
    bt = np.zeros((batch, pages_cap), np.int32)
    for b in range(batch):
        bt[b] = 1 + b * pages_cap + np.arange(pages_cap)
    bt = jnp.asarray(bt)
    lens0 = jnp.full((batch,), ctx_len, jnp.int32)
    toks0 = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch,)),
        jnp.int32)

    results = {}
    for mode in ("full", "nomerge", "noattn"):
        step = build_step(cfg, bt, page_size, num_pages, mode)

        @functools.partial(jax.jit, static_argnames=("steps",),
                           donate_argnums=(1, 2))
        def run(params, kp, vp, lens, toks, steps: int):
            def body(i, carry):
                kp, vp, lens, toks = carry
                logits, kp, vp = step(params, kp, vp, lens, toks)
                return (kp, vp, lens + 1,
                        jnp.argmax(logits, -1).astype(jnp.int32))
            return jax.lax.fori_loop(0, steps, body,
                                     (kp, vp, lens, toks))

        kp = k_pages + 0
        vp = v_pages + 0

        def window(n, kp, vp):
            t0 = time.perf_counter()
            kp, vp, lens, toks = run(params, kp, vp, lens0, toks0, n)
            int(np.asarray(toks)[0])
            return time.perf_counter() - t0, kp, vp

        for n in (8, 32):
            _, kp, vp = window(n, kp, vp)
        t_small, kp, vp = window(8, kp, vp)
        t_big, kp, vp = window(32, kp, vp)
        per = (t_big - t_small) / 24
        if per <= 0:
            per = t_big / 32
        results[mode] = round(per * 1e3, 2)
        print(mode, results[mode], "ms/step", flush=True)
    print({"step_ms": results,
           "attn_plus_merge_ms": round(
               results["full"] - results["noattn"], 2),
           "merge_ms": round(results["full"] - results["nomerge"], 2)})


if __name__ == "__main__":
    main()

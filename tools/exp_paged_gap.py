"""Chip experiment: the paged-vs-dense DMA gaps, decode AND prefill.

Decode (the original experiment): where do the ~9.5 ms between the
paged decode step (54.2 ms, b8 ctx256) and the fused-scan dense-cache
step (~44.7 ms) go? Times three variants of the b8/7B decode step under
the same fori-loop slope harness as bench_paged_decode_step:
  full     — the real serving step (paged_attention_stats + merge + scatter)
  noattn   — attention replaced by v (same matmuls/norms, no paged kernel)
  nomerge  — kernel runs, merge replaced by acc (no combine math)
full-noattn isolates the paged kernel + merge; full-nomerge isolates the
combine. If the kernel dominates, its (b, hkv, nblk)-grid 4 KB page DMAs
are the suspect (per-(page, head) copies are DMA-latency-bound).

Prefill (ISSUE 8 refresh): the dense-staging gather/scatter gap this
PR deleted, timed from the REAL entry points so the before/after stays
reproducible from one tool:
  dense    — llama.paged_prefill_partial: gather n_pp prefix pages into
             a dense temp cache, family forward, scatter the window back
  ragged   — llama.paged_prefill_ragged: attention reads the prefix
             pages in place, only the suffix scatter remains
  dma      — the gather + scatter of the dense sandwich with the layer
             math removed: the staging traffic in isolation
dense − ragged is the end-to-end win; dma bounds how much of it is pure
HBM round-trip (it grows with the prefix while ragged's suffix scatter
does not). Select with --decode / --prefill (default: both); --tiny
swaps in the tiny config for an off-chip smoke."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.llm.kernels.paged_attention import (
    LANE, merge_attention_partial, paged_attention_stats)
from bigdl_tpu.llm.models.llama import (LlamaConfig, _linear,
                                        attention_qkv, mlp, rms_norm,
                                        rope_cfg)

import bench as _bench


def build_step(cfg, bt, page, num_pages, mode: str):
    def step(params, k_pages, v_pages, lens, toks):
        b = toks.shape[0]
        L = cfg.num_hidden_layers
        kp_flat = k_pages.reshape((L * num_pages,) + k_pages.shape[2:])
        vp_flat = v_pages.reshape((L * num_pages,) + v_pages.shape[2:])
        x = params["embed_tokens"][toks][:, None]
        positions = lens[:, None].astype(jnp.int32)

        def layer_step(carry, inputs):
            x, = carry
            lp, l = inputs
            h = rms_norm(x, lp["input_layernorm"], cfg.rms_norm_eps)
            q, k, v = attention_qkv(lp, h, cfg)
            q = rope_cfg(q, positions, cfg)
            k = rope_cfg(k, positions, cfg)
            if mode == "noattn":
                attn = jnp.repeat(
                    v[:, 0], cfg.num_attention_heads
                    // cfg.num_key_value_heads, 1).astype(x.dtype)
            else:
                acc, m, lsum = paged_attention_stats(
                    q[:, 0], kp_flat, vp_flat, bt + l * num_pages, lens,
                    page_size=page)
                if mode == "nomerge":
                    attn = (acc / 256.0).astype(x.dtype)
                else:
                    attn = merge_attention_partial(
                        acc, m, lsum, q[:, 0], k[:, 0],
                        v[:, 0]).astype(x.dtype)
            x = x + _linear(lp["o_proj"], attn.reshape(b, 1, -1))
            h2 = rms_norm(x, lp["post_attention_layernorm"],
                          cfg.rms_norm_eps)
            x = x + mlp(lp, h2, x.dtype)
            return (x,), (k[:, 0], v[:, 0])

        (x,), (k_new, v_new) = jax.lax.scan(
            layer_step, (x,), (params["layers"],
                               jnp.arange(cfg.num_hidden_layers)))
        x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
        logits = _linear(params["lm_head"], x)
        pidx = lens // page
        slot = lens % page
        phys = bt[jnp.arange(b), pidx]
        k_pages = k_pages.at[:, phys, :, slot].set(
            k_new.transpose(1, 0, 2, 3).astype(k_pages.dtype))
        v_pages = v_pages.at[:, phys, :, slot].set(
            v_new.transpose(1, 0, 2, 3).astype(v_pages.dtype))
        return (logits[:, 0].astype(jnp.float32), k_pages, v_pages)

    return step


def decode_gap(batch=8, ctx_len=256, page_size=16, cfg=None):
    cfg = cfg or LlamaConfig.llama2_7b()
    params = _bench._synthetic_q4_llama_params(cfg)
    ppb = LANE // page_size
    cap = -(-(ctx_len + 160) // page_size)
    pages_cap = -(-cap // ppb) * ppb
    num_pages = 1 + batch * pages_cap
    nl, hkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                   cfg.head_dim)
    kk, kv = jax.random.split(jax.random.PRNGKey(1))
    shape = (nl, num_pages, hkv, page_size, hd)
    k_pages = jax.random.normal(kk, shape, jnp.bfloat16) * 0.1
    v_pages = jax.random.normal(kv, shape, jnp.bfloat16) * 0.1
    bt = np.zeros((batch, pages_cap), np.int32)
    for b in range(batch):
        bt[b] = 1 + b * pages_cap + np.arange(pages_cap)
    bt = jnp.asarray(bt)
    lens0 = jnp.full((batch,), ctx_len, jnp.int32)
    toks0 = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch,)),
        jnp.int32)

    results = {}
    for mode in ("full", "nomerge", "noattn"):
        step = build_step(cfg, bt, page_size, num_pages, mode)

        @functools.partial(jax.jit, static_argnames=("steps",),
                           donate_argnums=(1, 2))
        def run(params, kp, vp, lens, toks, steps: int):
            def body(i, carry):
                kp, vp, lens, toks = carry
                logits, kp, vp = step(params, kp, vp, lens, toks)
                return (kp, vp, lens + 1,
                        jnp.argmax(logits, -1).astype(jnp.int32))
            return jax.lax.fori_loop(0, steps, body,
                                     (kp, vp, lens, toks))

        kp = k_pages + 0
        vp = v_pages + 0

        def window(n, kp, vp):
            t0 = time.perf_counter()
            kp, vp, lens, toks = run(params, kp, vp, lens0, toks0, n)
            int(np.asarray(toks)[0])
            return time.perf_counter() - t0, kp, vp

        for n in (8, 32):
            _, kp, vp = window(n, kp, vp)
        t_small, kp, vp = window(8, kp, vp)
        t_big, kp, vp = window(32, kp, vp)
        per = (t_big - t_small) / 24
        if per <= 0:
            per = t_big / 32
        results[mode] = round(per * 1e3, 2)
        print(mode, results[mode], "ms/step", flush=True)
    out = {"step_ms": results,
           "attn_plus_merge_ms": round(
               results["full"] - results["noattn"], 2),
           "merge_ms": round(results["full"] - results["nomerge"], 2)}
    print(out)
    return out


def _build_dense_dma(cfg, page, n_pp, bucket):
    """The dense sandwich's memory traffic with the layer math removed:
    gather the n_pp prefix pages into a dense temp buffer, then scatter
    the page-aligned window back. What's left of paged_prefill_partial
    when the forward is deleted — the staging gap in isolation."""
    def dma(k_pages, v_pages, offset, prefix_ids, phys, slots):
        L = k_pages.shape[0]
        s_temp = n_pp * page + page + bucket
        window0 = (offset // page) * page

        def stage(pages):
            g = pages[:, prefix_ids].transpose(0, 1, 3, 2, 4)
            tmp = g.reshape(L, n_pp * page, *g.shape[3:])
            tmp = jnp.pad(tmp, ((0, 0), (0, s_temp - n_pp * page),
                                (0, 0), (0, 0)))
            w = jax.lax.dynamic_slice_in_dim(tmp, window0,
                                             page + bucket, axis=1)
            return pages.at[:, phys, :, slots].set(
                w.transpose(1, 0, 2, 3).astype(pages.dtype))

        return stage(k_pages), stage(v_pages)

    return dma


def prefill_gap(splits=None, page_size=16, cfg=None, repeats=8):
    """Partial-prefill dispatch time at several prefix/suffix splits,
    from the real ISSUE 5 / ISSUE 8 entry points (docstring above)."""
    from bigdl_tpu.llm.models import llama as _llama

    cfg = cfg or LlamaConfig.llama2_7b()
    if splits is None:
        limit = min(256, cfg.max_position_embeddings)
        splits = ((limit * 3 // 4, limit // 4),
                  (limit * 7 // 8, limit // 8))
    params = _bench._synthetic_q4_llama_params(cfg)
    nl, hkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                   cfg.head_dim)
    ppb = LANE // page_size
    top = max(s + t for s, t in splits)
    cap = -(-top // page_size)
    pages_cap = -(-cap // ppb) * ppb
    num_pages = 1 + 2 * pages_cap
    kk, kv = jax.random.split(jax.random.PRNGKey(2))
    shape = (nl, num_pages, hkv, page_size, hd)
    k_pages0 = jax.random.normal(kk, shape, jnp.bfloat16) * 0.1
    v_pages0 = jax.random.normal(kv, shape, jnp.bfloat16) * 0.1
    rs = np.random.RandomState(0)
    out = {}
    for prefix, suffix in splits:
        n_pp = 1 << max(0, (-(-prefix // page_size)) - 1).bit_length()
        bucket = max(page_size, 1 << (suffix - 1).bit_length())
        prefix_pages = list(range(1, 1 + -(-prefix // page_size)))
        own = list(range(1 + len(prefix_pages), 1 + pages_cap))
        row = np.zeros(pages_cap, np.int32)
        row[:len(prefix_pages) + len(own)] = prefix_pages + own
        T = prefix + suffix
        pos = prefix + np.arange(bucket)
        phys_b = np.where(pos < T, row[np.minimum(pos // page_size,
                                                  pages_cap - 1)],
                          0).astype(np.int32)
        slots_b = (pos % page_size).astype(np.int32)
        # the dense path's page-aligned window (page + bucket wide)
        w0 = (prefix // page_size) * page_size
        wpos = w0 + np.arange(page_size + bucket)
        phys_w = np.where((wpos >= prefix) & (wpos < T),
                          row[np.minimum(wpos // page_size,
                                         pages_cap - 1)],
                          0).astype(np.int32)
        slots_w = (wpos % page_size).astype(np.int32)
        pids = np.zeros(n_pp, np.int32)
        pids[:len(prefix_pages)] = prefix_pages
        toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, bucket)),
                           jnp.int32)
        args = dict(
            length=jnp.asarray(suffix, jnp.int32),
            offset=jnp.asarray(prefix, jnp.int32),
            pids=jnp.asarray(pids), bt=jnp.asarray(row),
            phys_b=jnp.asarray(phys_b), slots_b=jnp.asarray(slots_b),
            phys_w=jnp.asarray(phys_w), slots_w=jnp.asarray(slots_w))

        # cfg is a plain dataclass (unhashable): close over it like the
        # engine's builders do instead of marking it static
        npp_, bkt_ = n_pp, bucket
        dense = jax.jit(
            lambda params, kp, vp, *a: _llama.paged_prefill_partial(
                params, cfg, kp, vp, *a, page=page_size, n_pp=npp_,
                bucket=bkt_, cache_dtype=jnp.bfloat16),
            donate_argnums=(1, 2))
        ragged = jax.jit(
            lambda params, kp, vp, *a: _llama.paged_prefill_ragged(
                params, cfg, kp, vp, *a, page=page_size),
            donate_argnums=(1, 2))
        dma = jax.jit(_build_dense_dma(cfg, page_size, n_pp, bucket),
                      donate_argnums=(0, 1))
        zero = jnp.asarray(0, jnp.int32)

        def run_dense(kp, vp):
            out = dense(params, kp, vp, toks, args["length"],
                        args["offset"], args["pids"], args["phys_w"],
                        args["slots_w"])
            return out[0], out[1]

        def run_ragged(kp, vp):
            out = ragged(params, kp, vp, toks, args["length"],
                         args["offset"], args["bt"], args["phys_b"],
                         args["slots_b"], zero, zero)
            return out[0], out[1]

        def run_dma(kp, vp):
            return dma(kp, vp, args["offset"], args["pids"],
                       args["phys_w"], args["slots_w"])

        entry = {"prefix": prefix, "suffix": suffix, "n_pp": n_pp,
                 "bucket": bucket}
        for name, fn in (("dense", run_dense), ("ragged", run_ragged),
                         ("dma", run_dma)):
            kp, vp = k_pages0 + 0, v_pages0 + 0
            kp, vp = fn(kp, vp)                       # compile + warm
            jax.block_until_ready(kp)
            t0 = time.perf_counter()
            for _ in range(repeats):
                kp, vp = fn(kp, vp)
            jax.block_until_ready(kp)
            entry[f"{name}_ms"] = round(
                (time.perf_counter() - t0) / repeats * 1e3, 3)
        entry["staging_gap_ms"] = round(
            entry["dense_ms"] - entry["ragged_ms"], 3)
        out[f"{prefix}+{suffix}"] = entry
        print(entry, flush=True)
    return out


def main(argv=()):
    tiny = "--tiny" in argv
    cfg = LlamaConfig.tiny() if tiny else None
    which = [a for a in ("--decode", "--prefill") if a in argv] or \
        ["--decode", "--prefill"]
    out = {}
    if "--decode" in which:
        out["decode"] = decode_gap(cfg=cfg) if not tiny else decode_gap(
            batch=2, ctx_len=32, page_size=8, cfg=cfg)
    if "--prefill" in which:
        out["prefill"] = prefill_gap(cfg=cfg, page_size=8 if tiny
                                     else 16)
    return out


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
